"""Traceable collective primitives for use INSIDE jitted/shard_map'd code.

Reference parity: operators/collective/ (c_allreduce_sum, c_broadcast,
c_allgather, c_reducescatter, c_scatter, barrier). TPU-native: these are the
XLA collectives (psum/all_gather/ppermute) keyed by mesh axis name — the
ICI-native form. The `c_*` op names are kept for static programs; the
stream-sync ops (c_sync_calc_stream/c_sync_comm_stream) are no-ops because
XLA schedules communication (SURVEY.md §2.4).
"""
from __future__ import annotations


def c_allreduce_sum(x, axis_name="dp"):
    import jax

    return jax.lax.psum(x, axis_name)


def c_allreduce_max(x, axis_name="dp"):
    import jax

    return jax.lax.pmax(x, axis_name)


def c_allreduce_min(x, axis_name="dp"):
    import jax

    return jax.lax.pmin(x, axis_name)


def c_allreduce_prod(x, axis_name="dp"):
    import jax
    import jax.numpy as jnp

    return jnp.exp(jax.lax.psum(jnp.log(x), axis_name))


def c_allgather(x, axis_name="dp", tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, tiled=tiled)


def c_reducescatter(x, axis_name="dp"):
    import jax

    return jax.lax.psum_scatter(x, axis_name, tiled=True)


def c_broadcast(x, root=0, axis_name="dp"):
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis_name)
    src = jax.lax.psum(
        jnp.where(idx == root, x, jnp.zeros_like(x)), axis_name)
    return src


def c_ppermute(x, perm, axis_name="dp"):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def c_sync_calc_stream(x):
    return x


def c_sync_comm_stream(x):
    return x


def barrier_op(axis_name="dp"):
    import jax
    import jax.numpy as jnp

    return jax.lax.psum(jnp.zeros((), jnp.float32), axis_name)
