"""StrategyCompiler: meta-optimizer selection, ordering, conflicts.

Reference parity: fleet/base/strategy_compiler.py +
meta_optimizer_factory.py — each meta optimizer declares what it can
apply to and which others it disables; the compiler picks a valid
ordered subset or raises. The TPU mapping of each strategy lives in
meta_optimizers.py; this module owns the selection logic.
"""
from __future__ import annotations

from .meta_optimizers import _ORDER

# strategy -> strategies it DISABLES when applied (mirrors the
# meta-optimizers' self._meta_optimizers_black_list declarations)
_CONFLICTS = {
    "lamb": {"lars", "dgc"},
    "lars": {"lamb", "dgc"},
    "dgc": {"lamb", "lars"},
    "localsgd": {"dgc", "pipeline", "gradient_merge"},
    "pipeline": {"localsgd"},
}

# strategy -> predicate(inner_optimizer_name) it requires
_REQUIRES = {
    "dgc": lambda opt: opt in ("momentum", "sgd", None),
}


class StrategyCompiler:
    """generate_optimizer parity: validate + order the applied set."""

    def __init__(self):
        self._applied = []

    def generate_optimizer(self, strategy, inner_optimizer=None):
        requested = [k for k in _ORDER
                     if k != "graph_execution" and
                     getattr(strategy, k, False)]
        inner_name = None
        if inner_optimizer is not None:
            inner_name = type(inner_optimizer).__name__.lower().replace(
                "optimizer", "")
        # conflict check: a requested strategy may not be disabled by an
        # earlier (higher-priority) requested strategy
        applied = []
        for k in requested:
            blockers = [a for a in applied
                        if k in _CONFLICTS.get(a, ()) or
                        a in _CONFLICTS.get(k, ())]
            if blockers:
                raise ValueError(
                    f"DistributedStrategy conflict: {k!r} cannot be "
                    f"combined with {blockers} (reference strategy "
                    f"compiler black-list)")
            req = _REQUIRES.get(k)
            if req and not req(inner_name):
                raise ValueError(
                    f"strategy {k!r} requires a momentum/sgd inner "
                    f"optimizer, got {inner_name!r}")
            applied.append(k)
        self._applied = applied + ["graph_execution"]
        return self._applied

    @property
    def applied_meta_list(self):
        return [k + "_optimizer" for k in self._applied]
