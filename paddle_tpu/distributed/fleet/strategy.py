"""DistributedStrategy.

Reference parity: framework/distributed_strategy.proto:94 + the python
property wrapper distributed/fleet/base/distributed_strategy.py. Every knob
of the proto is present; TPU-native semantics noted per field.
"""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        # --- collective ---
        self.amp = False                      # → bf16 autocast
        self.amp_configs = {"init_loss_scaling": 32768.0,
                            "custom_white_list": [],
                            "custom_black_list": [],
                            "use_pure_fp16": False}
        self.recompute = False                # → jax.checkpoint
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False           # → accumulation window
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.localsgd = False                 # → periodic param psum
        self.localsgd_configs = {"k_steps": 1}
        self.dgc = False                      # deep gradient compression
        self.dgc_configs = {"rampup_begin_step": 0}
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 5e-4}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01}
        self.pipeline = False                 # → stage-sharded scan over ICI
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.sharding = False                 # → ZeRO param sharding (pjit)
        self.sharding_configs = {"sharding_degree": 1}
        self.tensor_parallel = False          # TPU extra: megatron-style TP
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sequence_parallel = False        # TPU extra: SP/ring attention
        self.sequence_parallel_configs = {"sequence_parallel_degree": 1}
        # --- collective comm tuning (XLA handles; accepted for parity) ---
        self.nccl_comm_num = 1
        self.hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 8
        self.sync_nccl_allreduce = True
        self.fuse_grad_size_in_MB = 32
        self.fuse_all_reduce_ops = True
        # --- parameter server ---
        self.a_sync = False
        self.a_sync_configs = {"k_steps": 0, "max_merge_var_num": 20,
                               "send_queue_size": 20,
                               "independent_recv_thread": False,
                               "thread_pool_size": 1,
                               "send_wait_times": 1,
                               "runtime_split_send_recv": False,
                               "launch_barrier": True}
        self.sync_mode = True
        # --- execution ---
        self.auto = False
        self.execution_strategy = None
        self.build_strategy = None
        self.fp16_allreduce = False
        self.find_unused_parameters = False
        self.without_graph_optimization = False

    # proto-style accessors
    def to_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
