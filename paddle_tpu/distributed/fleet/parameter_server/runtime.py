"""Parameter-server runtime glue.

Reference parity: fleet/runtime/parameter_server_runtime.py — wires
fleet.init_server/run_server/init_worker/stop_worker onto the native PS
stack (paddle_tpu.distributed.ps: csrc TCP RPC server + Communicator).
Role/endpoints come from the same env contract the reference uses
(PADDLE_PSERVER_ENDPOINTS, PADDLE_PORT, PADDLE_TRAINERS_NUM,
TRAINING_ROLE, PADDLE_TRAINER_ID).
"""
from __future__ import annotations

import os
import time

_server = None
_communicator = None


def _env(name, default=""):
    return os.environ.get(name, default)


def init_server(fleet_obj, *args):
    global _server
    from ...ps import PsServer

    port = int(_env("PADDLE_PORT", "0") or 0)
    trainers = int(_env("PADDLE_TRAINERS_NUM", "1") or 1)
    strategy = getattr(fleet_obj, "_strategy", None)
    lr = 0.01
    opt = "sgd"
    if strategy is not None:
        cfg = getattr(strategy, "a_sync_configs", {}) or {}
        opt = cfg.get("server_optimizer", opt)
        lr = float(cfg.get("server_lr", lr))
    _server = PsServer(port=port, trainers=trainers, optimizer=opt, lr=lr)
    return _server


def run_server(fleet_obj):
    if _server is None:
        init_server(fleet_obj)
    while True:  # listen_and_serv main loop
        time.sleep(0.2)


def get_server():
    return _server


def init_worker(fleet_obj):
    global _communicator
    from ...ps import Communicator

    endpoints = [e for e in _env("PADDLE_PSERVER_ENDPOINTS").split(",")
                 if e]
    if not endpoints:
        return None
    trainer_id = int(_env("PADDLE_TRAINER_ID", "0") or 0)
    strategy = getattr(fleet_obj, "_strategy", None)
    mode = "sync"
    geo_k = 4
    # async-SGD staleness knobs (DistributedStrategy.a_sync_configs):
    # bounded send queue + a short recv interval keep lr*(1+tau)*L < 2
    # on a contended host (the 8/50ms defaults diverged at lr=0.1)
    send_queue_size = 2
    recv_interval = 0.005
    if strategy is not None and getattr(strategy, "a_sync", False):
        cfg = getattr(strategy, "a_sync_configs", {}) or {}
        k_steps = int(cfg.get("k_steps", 0) or 0)
        mode = "geo" if k_steps > 0 else "async"
        geo_k = k_steps or geo_k
        send_queue_size = int(cfg.get("send_queue_size",
                                      send_queue_size) or send_queue_size)
        recv_interval = float(cfg.get("recv_interval", recv_interval)
                              or recv_interval)
    _communicator = Communicator(endpoints, mode=mode,
                                 trainer_id=trainer_id, geo_k=geo_k,
                                 send_queue_size=send_queue_size,
                                 recv_interval=recv_interval)
    _communicator.start()
    return _communicator


def get_communicator():
    return _communicator


def stop_worker(fleet_obj):
    global _communicator
    if _communicator is not None:
        _communicator.close()
        _communicator = None
