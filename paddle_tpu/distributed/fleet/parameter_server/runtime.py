"""Parameter-server runtime glue (reference:
fleet/runtime/parameter_server_runtime.py). The gRPC KV server itself lives
in paddle_tpu.distributed.ps; this module wires fleet init_worker/init_server
onto it."""
from __future__ import annotations


def init_worker(fleet_obj):
    from ...ps.worker import get_communicator

    comm = get_communicator()
    if comm is not None:
        comm.start()


def init_server(fleet_obj, *args):
    from ...ps.server import get_server

    get_server().init()


def run_server(fleet_obj):
    from ...ps.server import get_server

    get_server().run()


def stop_worker(fleet_obj):
    from ...ps.worker import get_communicator

    comm = get_communicator()
    if comm is not None:
        comm.stop()
