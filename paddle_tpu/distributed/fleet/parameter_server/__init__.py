from . import runtime  # noqa: F401
