"""Meta-optimizer stack: DistributedStrategy → training transforms.

Reference parity: python/paddle/distributed/fleet/meta_optimizers/
(amp_optimizer.py, recompute_optimizer.py, gradient_merge_optimizer.py,
localsgd_optimizer.py, dgc_optimizer.py, lars_optimizer.py,
lamb_optimizer.py, pipeline_optimizer.py, graph_execution_optimizer.py)
ordered by base/strategy_compiler.py.

TPU-native design: instead of rewriting ProgramDescs, each strategy knob
maps onto the SPMD train step (paddle_tpu.parallel.SpmdTrainer):
  amp            → bf16 compute dtype (+ loss scaling only for fp16)
  recompute      → jax.remat over the layer apply
  gradient_merge → lax.scan microbatch accumulation (grad_accum)
  dgc            → top-k sparsified grads + error feedback (fopt.dgc)
  lars / lamb    → optimizer-rule swap (fopt.lars_momentum / fopt.lamb)
  localsgd       → periodic cross-replica parameter averaging
  pipeline       → GPipe stage schedule (paddle_tpu.parallel.pipeline)
  sharding       → ZeRO-style: optimizer state inherits param shardings
  graph exec     → the jitted SPMD step itself (always on)
"""
from __future__ import annotations

from ...optimizer import functional as fopt

_ORDER = ["amp", "recompute", "gradient_merge", "localsgd", "dgc",
          "lars", "lamb", "pipeline", "graph_execution"]


def applied_meta_list(strategy):
    """Which meta-optimizers the compiler would apply, in order
    (StrategyCompiler ordering parity — useful for tests/logging)."""
    out = []
    for k in _ORDER:
        if k == "graph_execution" or getattr(strategy, k, False):
            out.append(k + "_optimizer")
    return out


def transform_from_strategy(strategy, base_tx=None, learning_rate=None):
    """Build the functional optimizer Transform implied by the strategy
    (lars/lamb swap + dgc wrap), starting from base_tx or SGD."""
    lr = learning_rate if learning_rate is not None else 0.01
    tx = base_tx or fopt.sgd(lr)
    if getattr(strategy, "lamb", False):
        wd = strategy.lamb_configs.get("lamb_weight_decay", 0.01)
        tx = fopt.lamb(lr, weight_decay=wd)
    if getattr(strategy, "lars", False):
        cfg = strategy.lars_configs
        tx = fopt.lars_momentum(
            lr, lars_coeff=cfg.get("lars_coeff", 0.001),
            lars_weight_decay=cfg.get("lars_weight_decay", 5e-4))
    if getattr(strategy, "dgc", False):
        tx = fopt.dgc(tx)
    return tx


def spmd_trainer_kwargs(strategy):
    """SpmdTrainer constructor kwargs implied by the strategy."""
    kw = {}
    if getattr(strategy, "amp", False):
        # bf16-first AMP: TPUs natively accumulate bf16 matmuls in f32, so
        # no loss scaling is needed (amp_configs' loss scaling is an fp16
        # artifact kept for API parity)
        kw["compute_dtype"] = "bfloat16"
    if getattr(strategy, "recompute", False):
        kw["remat"] = True
    if getattr(strategy, "gradient_merge", False):
        kw["grad_accum"] = int(
            strategy.gradient_merge_configs.get("k_steps", 1))
    return kw


def build_spmd_trainer(layer, loss_fn, strategy, base_optimizer=None,
                       learning_rate=None, mesh=None, rules=None):
    """GraphExecutionOptimizer equivalent: the strategy-configured SPMD
    train step (one jitted fn; XLA owns collectives/fusion/overlap)."""
    from ...parallel import SpmdTrainer

    base_tx = None
    if base_optimizer is not None:
        base_tx = base_optimizer if isinstance(
            base_optimizer, fopt.Transform) else fopt.from_eager(
                base_optimizer)
    tx = transform_from_strategy(strategy, base_tx, learning_rate)
    return SpmdTrainer(layer, loss_fn, tx, mesh=mesh, rules=rules,
                       **spmd_trainer_kwargs(strategy))


class LocalSGDSync:
    """localsgd_optimizer.py capability: train locally, every k_steps
    average parameters across data-parallel replicas."""

    def __init__(self, k_steps=1):
        self.k = max(1, int(k_steps))
        self._step = 0

    def maybe_sync(self, params):
        """params: dict name->array. Returns possibly-averaged params."""
        self._step += 1
        if self._step % self.k != 0:
            return params
        from .. import all_reduce_mean_tree

        return all_reduce_mean_tree(params)
