"""Fleet: the distributed-training facade.

Reference parity: python/paddle/distributed/fleet/ — Fleet
(base/fleet_base.py:63), DistributedStrategy (base/distributed_strategy.py
over framework/distributed_strategy.proto:94), the meta-optimizer stack
(meta_optimizers/: AMP, Recompute, GradientMerge, LocalSGD, DGC, Lars, Lamb,
Pipeline, ParameterServer, GraphExecution picked by
base/strategy_compiler.py). TPU-native design: collective mode lowers to
SPMD (paddle_tpu.parallel) over a jax Mesh — strategy knobs map to sharding
+ jax transforms (amp→bf16 autocast, recompute→jax.checkpoint,
gradient_merge→accumulation loop) instead of program rewrites.
"""
from __future__ import annotations

import os

from ...core.tensor import Tensor
from . import metrics  # noqa: F401  (fleet.metrics.sum/max/auc/...)
from .strategy import DistributedStrategy  # noqa: F401
from .role_maker import (PaddleCloudRoleMaker, Role,  # noqa: F401
                         UserDefinedRoleMaker)


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy = None
        self._user_defined_optimizer = None
        self._is_initialized = False

    # ----------------- init / role ----------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        from .. import init_parallel_env

        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._strategy = strategy or DistributedStrategy()
        self._is_collective = is_collective
        if is_collective:
            init_parallel_env()
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        from .. import get_rank

        return get_rank()

    def worker_num(self):
        from .. import get_world_size

        return get_world_size()

    def is_worker(self):
        return self._role_maker is None or self._role_maker._is_worker()

    def is_server(self):
        return self._role_maker is not None and self._role_maker._is_server()

    def server_num(self):
        return self._role_maker._server_num() if self._role_maker else 0

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "").split(",")
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .. import barrier

        barrier()

    # ----------------- optimizer path ----------------
    def distributed_optimizer(self, optimizer, strategy=None):
        from .strategy_compiler import StrategyCompiler

        if strategy is not None:
            self._strategy = strategy
        self._user_defined_optimizer = optimizer
        st = self._strategy or DistributedStrategy()
        # validate + order the strategy set (raises on conflicts — the
        # reference StrategyCompiler's black-list behavior)
        compiler = StrategyCompiler()
        compiler.generate_optimizer(st, optimizer)
        self._strategy_compiler = compiler
        return MetaOptimizer(optimizer, st, self)

    def distributed_model(self, model):
        from ..parallel import DataParallel

        return DataParallel(model)

    # ----------------- PS runtime ----------------
    def init_worker(self):
        from .parameter_server import runtime

        runtime.init_worker(self)

    def init_server(self, *args, **kwargs):
        from .parameter_server import runtime

        runtime.init_server(self, *args)

    def run_server(self):
        from .parameter_server import runtime

        runtime.run_server(self)

    def stop_worker(self):
        from .parameter_server import runtime

        runtime.stop_worker(self)

    # ----------------- save ----------------
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ...fluid.io import save_inference_model

        return save_inference_model(dirname, feeded_var_names, target_vars,
                                    executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ...fluid.io import save_persistables

        return save_persistables(executor, dirname, main_program)


class MetaOptimizer:
    """The strategy-compiler stack (base/strategy_compiler.py parity):
    wraps the user optimizer per DistributedStrategy knobs."""

    def __init__(self, inner, strategy, fleet_obj):
        self._inner = inner
        self._strategy = strategy
        self._fleet = fleet_obj

    # eager path -------------------------------------------------------
    def step(self):
        self._maybe_wrap_eager()
        self._inner.step()

    def clear_grad(self):
        self._inner.clear_grad()

    @property
    def _parameters(self):
        return getattr(self._inner, "_parameters", [])

    def _maybe_wrap_eager(self):
        pass

    def state_dict(self):
        return self._inner.state_dict()

    # static path ------------------------------------------------------
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """Apply meta-optimizations then the inner optimizer (mirrors
        StrategyCompiler ordering: AMP → Recompute → ... → inner)."""
        s = self._strategy
        inner = self._inner
        if hasattr(loss, "block"):  # static graph program
            from ...fluid.optimizer import RecomputeOptimizer

            opt = inner
            if s.recompute:
                ro = RecomputeOptimizer(opt)
                ro._set_checkpoints(s.recompute_configs.get(
                    "checkpoints", []))
                opt = ro
            return opt.minimize(loss, startup_program, parameter_list,
                                no_grad_set)
        # eager
        loss.backward()
        self.step()
        return None, None


fleet = Fleet()

# module-level convenience mirroring `from paddle.distributed import fleet`
init = fleet.init
is_first_worker = fleet.is_first_worker
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_worker = fleet.is_worker
is_server = fleet.is_server
distributed_optimizer = fleet.distributed_optimizer
distributed_model = fleet.distributed_model
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
stop_worker = fleet.stop_worker
barrier_worker = fleet.barrier_worker
save_inference_model = fleet.save_inference_model
save_persistables = fleet.save_persistables
worker_endpoints = fleet.worker_endpoints
server_endpoints = fleet.server_endpoints


class UtilBase:
    def all_reduce(self, input, mode="sum"):
        from . import metrics as _m

        return _m._all_reduce(input, mode)

    def barrier(self):
        fleet.barrier_worker()


util = UtilBase()
