"""Role makers.

Reference parity: fluid/incubate/fleet/base/role_maker.py (:190 MPI legacy,
:1132 UserDefinedRoleMaker) + fleet/base/role_maker.py PaddleCloudRoleMaker
(env-driven TRAINING_ROLE / PADDLE_* variables).
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def _is_worker(self):
        raise NotImplementedError

    def _is_server(self):
        raise NotImplementedError

    def _worker_num(self):
        raise NotImplementedError

    def _server_num(self):
        raise NotImplementedError

    def _worker_index(self):
        raise NotImplementedError


class PaddleCloudRoleMaker(RoleMakerBase):
    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        self._role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._server_eps = [e for e in os.environ.get(
            "PADDLE_PSERVER_ENDPOINTS", "").split(",") if e]
        self._worker_eps = [e for e in os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
        self._current_ep = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    def _is_worker(self):
        return self._role in ("TRAINER", "WORKER")

    def _is_server(self):
        return self._role == "PSERVER"

    def _is_first_worker(self):
        return self._is_worker() and self._trainer_id == 0

    def _worker_num(self):
        return self._trainers_num

    def _server_num(self):
        return len(self._server_eps)

    def _worker_index(self):
        return self._trainer_id

    def _server_index(self):
        if self._current_ep in self._server_eps:
            return self._server_eps.index(self._current_ep)
        return 0

    def _get_pserver_endpoints(self):
        return self._server_eps

    def _get_trainer_endpoints(self):
        return self._worker_eps


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kwargs):
        self._cur_id = current_id
        self._role = role
        self._worker_num_ = worker_num
        self._server_eps = server_endpoints or []

    def _is_worker(self):
        return self._role == Role.WORKER

    def _is_server(self):
        return self._role == Role.SERVER

    def _worker_num(self):
        return self._worker_num_

    def _server_num(self):
        return len(self._server_eps)

    def _worker_index(self):
        return self._cur_id

    def _server_index(self):
        return self._cur_id

    def _get_pserver_endpoints(self):
        return self._server_eps
