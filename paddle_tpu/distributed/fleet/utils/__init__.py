from .fs import HDFSClient, LocalFS  # noqa: F401

__all__ = ["LocalFS", "HDFSClient"]
