"""Filesystem clients (fleet/utils/fs.py + framework/io/fs.cc parity).

LocalFS wraps the host filesystem; HDFSClient shells out to the hadoop
CLI exactly like the reference (fs.cc pipes `hadoop fs -ls` etc through
popen). The command prefix is configurable so GCS (`gsutil`) or a test
shim can substitute — the shell-pipe framework IS the capability; no
egress happens unless the operator provides a working client binary.
"""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(RuntimeError):
    pass


class LocalFS:
    """fleet/utils/fs.py LocalFS parity."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for n in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, n))
             else files).append(n)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        elif os.path.exists(dst):
            raise ExecuteError(
                f"mv: {dst!r} exists (pass overwrite=True to replace)")
        os.rename(src, dst)

    def upload(self, local_path, path, multi_processes=1,
               overwrite=False):
        if overwrite:
            self.delete(path)
        shutil.copy(local_path, path)

    def download(self, path, local_path, multi_processes=1,
                 overwrite=False):
        if overwrite and os.path.exists(local_path):
            os.remove(local_path)
        shutil.copy(path, local_path)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise ExecuteError(f"{path} exists")
        open(path, "a").close()

    def cat(self, path):
        with open(path, "rb") as f:
            return f.read()


class HDFSClient:
    """Shell-pipe HDFS/remote-store client (fs.cc HDFS command parity).

    hadoop_home/configs follow the reference constructor; `cmd_prefix`
    overrides the executable (e.g. ["gsutil"] for GCS-style stores or a
    test shim script).
    """

    def __init__(self, hadoop_home=None, configs=None, cmd_prefix=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        if cmd_prefix is not None:
            self._base = list(cmd_prefix)
        else:
            exe = os.path.join(hadoop_home, "bin", "hadoop") \
                if hadoop_home else "hadoop"
            self._base = [exe, "fs"]
            for k, v in (configs or {}).items():
                self._base += ["-D", f"{k}={v}"]
        self._timeout = time_out / 1000.0

    def _run(self, *args, check=True):
        cmd = self._base + list(args)
        try:
            # binary pipes: cat must pass bytes through untouched
            p = subprocess.run(cmd, capture_output=True,
                               timeout=self._timeout)
        except FileNotFoundError as e:
            raise ExecuteError(
                f"remote-fs client binary not found: {cmd[0]!r} — install "
                f"the hadoop/gsutil CLI or pass cmd_prefix") from e
        except subprocess.TimeoutExpired as e:
            raise ExecuteError(f"{' '.join(cmd)} timed out") from e
        if check and p.returncode != 0:
            err = p.stderr.decode("utf-8", "replace").strip()[:500]
            raise ExecuteError(
                f"{' '.join(cmd)} failed rc={p.returncode}: {err}")
        return p

    def ls_dir(self, path):
        p = self._run("-ls", path, check=False)
        dirs, files = [], []
        for line in p.stdout.decode("utf-8", "replace").splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = parts[-1].rstrip("/").split("/")[-1]
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return self._run("-test", "-e", path,
                         check=False).returncode == 0

    def is_dir(self, path):
        return self._run("-test", "-d", path,
                         check=False).returncode == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path, check=False)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        self._run("-mv", src, dst)

    def upload(self, local_path, path, multi_processes=1, overwrite=False):
        if overwrite:
            self.delete(path)
        self._run("-put", local_path, path)

    def download(self, path, local_path, multi_processes=1,
                 overwrite=False):
        if overwrite and os.path.exists(local_path):
            os.remove(local_path)
        self._run("-get", path, local_path)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise ExecuteError(f"{path} exists")
        self._run("-touchz", path)

    def cat(self, path):
        return self._run("-cat", path).stdout  # bytes, like LocalFS.cat
