"""Fleet distributed metrics: sum/max/min/auc/mae/rmse/mse/acc aggregated
over all trainers.

Reference parity: python/paddle/distributed/fleet/metrics/metric.py (gloo /
pslib allreduce of numpy stats) — each trainer holds local metric buckets
(e.g. the stat_pos/stat_neg outputs of the auc op) and the fleet metric
reduces them across workers before the final formula.

TPU-native design: the reduction is HOST-side (these are CPU numpy stats,
not device tensors) over the KV rendezvous store
(paddle_tpu.distributed.rendezvous.TCPStore — the gloo-store equivalent),
so it works in PS mode, collective mode, and single-process mode alike.
Call `init_metric_context(store, rank, world)` once per trainer, or set
`PT_METRIC_STORE=<host:port>` (+ the standard PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM env the launcher already exports) and the context
auto-connects on first use. With no context configured, world=1 semantics
apply (a no-op reduce).
"""
from __future__ import annotations

import base64
import builtins
import os

import numpy as np

_CTX = {"store": None, "rank": 0, "world": 1, "round": 0, "env_tried": False}


def init_metric_context(store, rank, world):
    """Install the cross-trainer reduce context (a rendezvous store)."""
    _CTX.update(store=store, rank=int(rank), world=int(world), round=0,
                env_tried=True)


def _maybe_init_from_env():
    if _CTX["store"] is not None or _CTX["env_tried"]:
        return
    _CTX["env_tried"] = True
    ep = os.environ.get("PT_METRIC_STORE")
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if not ep or world <= 1:
        return
    from ..rendezvous import TCPStore

    host, port = ep.rsplit(":", 1)
    _CTX.update(
        store=TCPStore(host, int(port), is_master=False, world_size=world),
        rank=int(os.environ.get("PADDLE_TRAINER_ID", "0")), world=world)


def _resolve(x, scope):
    """numpy array | paddle Tensor | fluid Variable | var-name string."""
    if isinstance(x, str):
        name = x
    elif hasattr(x, "name") and not hasattr(x, "__array__") and not hasattr(
            x, "_data"):
        name = x.name  # fluid Variable
    else:
        if hasattr(x, "_data"):
            return np.asarray(x._data)
        return np.asarray(x)
    if scope is None:
        from ...fluid.executor import global_scope

        scope = global_scope()
    var = scope.find_var(name)
    if var is None:
        raise KeyError(f"fleet.metrics: variable {name!r} not in scope")
    return np.asarray(var.get_tensor())


def _all_reduce(arr, mode="sum"):
    """Host-side allreduce of a numpy array across trainers via the KV
    store: every rank publishes its buffer, every rank reduces all of
    them (symmetric, no root)."""
    _maybe_init_from_env()
    store, rank, world = _CTX["store"], _CTX["rank"], _CTX["world"]
    arr = np.asarray(arr, np.float64)
    if store is None or world <= 1:
        return arr
    rnd = _CTX["round"]
    _CTX["round"] = rnd + 1
    # PT_METRIC_NS namespaces key rounds per job incarnation so an elastic
    # restart against a long-lived store cannot read a crashed run's
    # leftover buffers (launcher exports one value to every rank)
    ns = os.environ.get("PT_METRIC_NS", "")
    key = f"__fleet_metric_{ns}_{rnd}"
    store.set(f"{key}_{rank}",
              base64.b64encode(arr.astype("<f8").tobytes()).decode())
    parts = []
    for r in range(world):
        raw = base64.b64decode(store.get(f"{key}_{r}"))
        parts.append(np.frombuffer(raw, "<f8").reshape(arr.shape))
    op = {"sum": np.add, "max": np.maximum, "min": np.minimum}[mode]
    out = parts[0]
    for p in parts[1:]:
        out = op(out, p)
    # bounded store: last reader deletes the round's keys (every rank
    # bumps a done-counter once it has read all parts)
    if hasattr(store, "add") and hasattr(store, "delete"):
        done = store.add(f"{key}__done", 1)
        if done >= world:
            for r in range(world):
                store.delete(f"{key}_{r}")
            store.delete(f"{key}__done")
    return out


def sum(input, scope=None):  # noqa: A001 — reference API name
    """Distributed sum of a local stat array."""
    return _all_reduce(_resolve(input, scope), "sum")


def max(input, scope=None):  # noqa: A001
    """Distributed elementwise max of a local stat array."""
    return _all_reduce(_resolve(input, scope), "max")


def min(input, scope=None):  # noqa: A001
    """Distributed elementwise min of a local stat array."""
    return _all_reduce(_resolve(input, scope), "min")


def auc(stat_pos, stat_neg, scope=None):
    """Distributed AUC from the bucketed stat_pos/stat_neg outputs of the
    auc op: allreduce both histograms, then integrate the ROC area
    bucket-by-bucket from the highest threshold down."""
    pos = _all_reduce(_resolve(stat_pos, scope).reshape(-1), "sum")
    neg = _all_reduce(_resolve(stat_neg, scope).reshape(-1), "sum")
    area = tp = fp = 0.0
    total = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_tp = tp + pos[i]
        new_fp = fp + neg[i]
        total += pos[i] + neg[i]
        area += (new_fp - fp) * (tp + new_tp) / 2.0
        tp, fp = new_tp, new_fp
    if tp * fp == 0 or total == 0:
        return 0.5
    return float(area / (tp * fp))


def _reduced_scalar(x, scope):
    return float(_all_reduce(_resolve(x, scope).reshape(-1), "sum").sum())


def mae(abserr, total_ins_num, scope=None):
    """Distributed mean absolute error from (sum |err|, instance count)."""
    err = _reduced_scalar(abserr, scope)
    n = _reduced_scalar(total_ins_num, scope)
    return err / builtins.max(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None):
    """Distributed root mean squared error from (sum err^2, count)."""
    return float(np.sqrt(mse(sqrerr, total_ins_num, scope)))


def mse(sqrerr, total_ins_num, scope=None):
    """Distributed mean squared error from (sum err^2, count)."""
    err = _reduced_scalar(sqrerr, scope)
    n = _reduced_scalar(total_ins_num, scope)
    return err / builtins.max(n, 1.0)


def acc(correct, total, scope=None):
    """Distributed accuracy from (correct count, total count)."""
    c = _reduced_scalar(correct, scope)
    t = _reduced_scalar(total, scope)
    return c / builtins.max(t, 1.0)
