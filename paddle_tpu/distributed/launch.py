"""Multi-process launcher.

Reference parity: python/paddle/distributed/launch.py and
fleet/launch.py (:188 launch_collective, :227 launch_ps) + the watchdog in
distributed/utils.py:411 (watch_local_trainers / terminate_local_procs —
if any local proc dies, kill the pod and exit nonzero).

TPU-native notes: in collective mode each rank gets the reference env
contract (PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS,
PADDLE_CURRENT_ENDPOINT) plus the jax multi-host coordinates
(JAX_COORDINATOR_ADDRESS, JAX_NUM_PROCESSES, JAX_PROCESS_ID) so
`jax.distributed.initialize()` picks them up over DCN. In PS mode pserver
processes run `paddle_tpu.distributed.ps` servers and trainers get
PADDLE_PSERVER_ENDPOINTS / TRAINING_ROLE.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch_collective", "launch_ps", "main"]


def _free_ports(n, host="127.0.0.1"):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind((host, 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def terminate_procs(procs):
    """terminate_local_procs (distributed/utils.py:252) parity."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + 5
    for p in procs:
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            p.kill()


def watch_procs(procs, tags):
    """watch_local_trainers parity: block until all exit; if any dies
    nonzero, kill the rest and return its code."""
    try:
        while True:
            alive = False
            for p, tag in zip(procs, tags):
                rc = p.poll()
                if rc is None:
                    alive = True
                elif rc != 0:
                    sys.stderr.write(
                        f"[launch] {tag} exited with code {rc}; "
                        "terminating remaining processes\n")
                    terminate_procs(procs)
                    return rc
            if not alive:
                return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        terminate_procs(procs)
        return 1


def launch_collective(script_args, nproc=2, host="127.0.0.1",
                      started_port=None, log_dir=None, extra_env=None):
    """Spawn nproc ranks of `python script args...` with the collective
    env contract. Returns the watchdog's exit code."""
    ports = _free_ports(nproc) if started_port is None else \
        list(range(started_port, started_port + nproc))
    endpoints = ",".join(f"{host}:{p}" for p in ports)
    procs, tags = [], []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nproc),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT": f"{host}:{ports[rank]}",
            "TRAINING_ROLE": "TRAINER",
            # jax.distributed.initialize() coordinates
            "JAX_COORDINATOR_ADDRESS": f"{host}:{ports[0]}",
            "JAX_NUM_PROCESSES": str(nproc),
            "JAX_PROCESS_ID": str(rank),
        })
        env.update(extra_env or {})
        out = None
        if log_dir:
            out = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, *script_args], env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None))
        tags.append(f"trainer {rank}")
    return watch_procs(procs, tags)


def launch_ps(script_args, num_servers=1, num_trainers=1,
              host="127.0.0.1", server_optimizer="sgd", server_lr=0.01,
              log_dir=None, extra_env=None):
    """Spawn pserver processes (native PS servers) + trainer processes
    (fleet/launch.py:227 launch_ps parity)."""
    ports = _free_ports(num_servers)
    endpoints = ",".join(f"{host}:{p}" for p in ports)
    procs, tags = [], []
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    for sid, port in enumerate(ports):
        env = dict(os.environ)
        env.update({
            "TRAINING_ROLE": "PSERVER",
            "PADDLE_PORT": str(port),
            "PADDLE_TRAINERS_NUM": str(num_trainers),
            "POD_IP": host,
        })
        env.update(extra_env or {})
        out = None
        if log_dir:
            out = open(os.path.join(log_dir, f"serverlog.{sid}"), "w")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.ps",
             "--port", str(port), "--trainers", str(num_trainers),
             "--optimizer", server_optimizer, "--lr", str(server_lr)],
            env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None))
        tags.append(f"pserver {sid}")
    trainer_procs = []
    for rank in range(num_trainers):
        env = dict(os.environ)
        env.update({
            "TRAINING_ROLE": "TRAINER",
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(num_trainers),
            "PADDLE_PSERVER_ENDPOINTS": endpoints,
        })
        env.update(extra_env or {})
        out = None
        if log_dir:
            out = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
        trainer_procs.append(subprocess.Popen(
            [sys.executable, *script_args], env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None))
        tags.append(f"trainer {rank}")
    all_procs = procs + trainer_procs
    # trainers finishing cleanly ends the job; then stop servers
    rc = 0
    try:
        while True:
            t_alive = False
            for i, p in enumerate(trainer_procs):
                prc = p.poll()
                if prc is None:
                    t_alive = True
                elif prc != 0:
                    sys.stderr.write(
                        f"[launch] trainer {i} exited {prc}; "
                        "terminating job\n")
                    terminate_procs(all_procs)
                    return prc
            for i, p in enumerate(procs):
                prc = p.poll()
                if prc is not None and t_alive:
                    sys.stderr.write(
                        f"[launch] pserver {i} died ({prc}); "
                        "terminating job\n")
                    terminate_procs(all_procs)
                    return prc or 1
            if not t_alive:
                break
            time.sleep(0.2)
    except KeyboardInterrupt:
        rc = 1
    terminate_procs(procs)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu multi-process launcher (fleetrun parity)")
    ap.add_argument("--nproc_per_node", type=int, default=None)
    ap.add_argument("--server_num", type=int, default=0)
    ap.add_argument("--worker_num", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--started_port", type=int, default=None)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--server_optimizer", default="sgd")
    ap.add_argument("--server_lr", type=float, default=0.01)
    ap.add_argument("script", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    script = [a for a in args.script if a != "--"]
    if not script:
        ap.error("no training script given")
    if args.server_num > 0:
        return launch_ps(script, num_servers=args.server_num,
                         num_trainers=args.worker_num or 1,
                         host=args.host, log_dir=args.log_dir,
                         server_optimizer=args.server_optimizer,
                         server_lr=args.server_lr)
    nproc = args.nproc_per_node or 1
    return launch_collective(script, nproc=nproc, host=args.host,
                             started_port=args.started_port,
                             log_dir=args.log_dir)


if __name__ == "__main__":
    sys.exit(main())
