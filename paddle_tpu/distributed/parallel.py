"""Dygraph DataParallel.

Reference parity: fluid/dygraph/parallel.py:236 (DataParallel, scale_loss
:337, apply_collective_grads :449 — coalesced bucket allreduce). TPU-native
design: under a 1-process mesh the SPMD train step (paddle_tpu.parallel)
handles gradient sync inside XLA; this eager wrapper reproduces the
bucketed-allreduce semantics for the multi-process eager path.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import _psum_all_devices, get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size_mb=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        super().__init__()
        self._layers = layers
        self._comm_buffer_bytes = comm_buffer_size_mb * 1024 * 1024

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        # reference scales by 1/nranks before allreduce-sum
        world = get_world_size()
        if world == 1:
            return loss
        return loss / world

    def apply_collective_grads(self):
        """Coalesce grads into fixed-size buckets, one allreduce per bucket
        (_coalesce_tensors parallel.py:409 / split back :434)."""
        import jax.numpy as jnp

        world = get_world_size()
        if world == 1:
            return
        grads = [(p, p.grad) for p in self._layers.parameters()
                 if p.grad is not None]
        bucket, bucket_bytes = [], 0
        buckets = [bucket]
        for p, g in grads:
            nbytes = g._data.size * g._data.dtype.itemsize
            if bucket_bytes + nbytes > self._comm_buffer_bytes and bucket:
                bucket = []
                buckets.append(bucket)
                bucket_bytes = 0
            bucket.append((p, g))
            bucket_bytes += nbytes
        for bucket in buckets:
            if not bucket:
                continue
            flat = jnp.concatenate(
                [g._data.reshape(-1).astype(jnp.float32)
                 for _, g in bucket])
            flat = _psum_all_devices(flat)
            ofs = 0
            for p, g in bucket:
                n = g._data.size
                g._data = flat[ofs:ofs + n].reshape(
                    g._data.shape).astype(g._data.dtype)
                ofs += n

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)
