"""Parameter-server training mode.

Reference parity: the PS family of operators/distributed/ — RPC
client/server (grpc/brpc), `Communicator` (communicator.h:180 sync /
:253 async / geo via env), parameter_send/recv, large-scale sparse KV
(large_scale_kv.h:762), listen_and_serv server-side optimize blocks
(listen_and_serv_op.h:56), heartbeat monitor (heart_beat_monitor.h:54),
plus the Python-side fleet PS runtime
(distributed/fleet/runtime/parameter_server_runtime.py).

TPU-native design (SURVEY.md §2.3): pservers are CPU-host processes running
the native TCP RPC server (csrc/ptcore/ps_server.cc) with server-side
optimizer rules; TPU workers run jitted XLA compute and exchange
dense/sparse tensors with the server between steps (host callbacks —
never inside the XLA computation). Sharding across multiple pservers is
by hash over parameter names.
"""
from __future__ import annotations

import ctypes
import threading
import time

import numpy as np

from ...core.native import load_library

__all__ = ["PsServer", "PsClient", "Communicator", "DistributedLookupTable",
           "run_pserver", "SparsePrefetcher", "MergedSparseStream"]


class PsServer:
    """In-process native PS server (one per pserver host).

    optimizer: 'sgd' | 'momentum' | 'adam' — the server-side optimize
    rule applied to pushed dense grads (listen_and_serv capability).
    """

    def __init__(self, port=0, trainers=1, optimizer="sgd", lr=0.01):
        self._lib = load_library(required=True)
        self._h = self._lib.pt_ps_server_start(
            port, trainers, optimizer.encode(), float(lr))
        if not self._h:
            raise RuntimeError(f"PS server failed to bind port {port}")

    @property
    def port(self):
        return self._lib.pt_ps_server_port(self._h)

    def stale_trainers(self, timeout_ms=10000):
        """Heartbeat monitor: trainers not seen within timeout."""
        return self._lib.pt_ps_server_stale(self._h, timeout_ms)

    def shutdown_requested(self):
        """True once a client issued the shutdown RPC."""
        return bool(self._lib.pt_ps_server_shutdown_requested(self._h))

    def stop(self):
        if self._h:
            self._lib.pt_ps_server_stop(self._h)
            self._lib.pt_ps_server_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PsClient:
    """Native RPC client for one pserver endpoint.

    Thread-safe: one framed-RPC socket underlies the handle, so every
    RPC runs under a lock — the async Communicator's send and recv
    threads (and the dataset engine's Downpour plane) share one client,
    and interleaved frames corrupt the protocol ("send failed" rc=-1).
    """

    _RPC_METHODS = ("init_dense", "push_dense", "pull_dense",
                    "push_sparse", "pull_dense_if_newer", "pull_sparse",
                    "barrier", "heartbeat", "shutdown_server",
                    "save", "load")

    def __init__(self, host="127.0.0.1", port=0):
        import functools
        import threading

        self._lib = load_library(required=True)
        self._host, self._port = host, port
        self._h = self._lib.pt_ps_connect(host.encode(), port)
        if not self._h:
            raise ConnectionError(f"cannot connect to pserver {host}:{port}")
        self._mu = threading.Lock()
        for name in self._RPC_METHODS:
            fn = getattr(self, name)

            def locked(*a, _fn=fn, **k):
                with self._mu:
                    # close() nulls the handle under this same lock; a
                    # late RPC from a lingering worker thread must fail
                    # cleanly, not hand a freed pointer to native code
                    if self._h is None:
                        raise ConnectionError("ps client is closed")
                    try:
                        return _fn(*a, **k)
                    except RuntimeError as e:
                        # transient transport failure: reconnect once and
                        # retry (AsyncCommunicator resilience — a dead
                        # socket must not silently kill the send thread)
                        if "send" not in str(e) and "recv" not in str(e):
                            raise
                        self._reconnect()
                        return _fn(*a, **k)

            setattr(self, name, functools.wraps(fn)(locked))

    def _reconnect(self):
        if self._h:
            try:
                self._lib.pt_ps_disconnect(self._h)
            except Exception:
                pass
        self._h = self._lib.pt_ps_connect(self._host.encode(), self._port)
        if not self._h:
            raise ConnectionError(
                f"cannot reconnect to pserver {self._host}:{self._port}")

    def _ck(self, rc, what):
        if rc != 0:
            raise RuntimeError(
                f"ps {what} failed (rc={rc}): "
                + self._lib.pt_ps_client_error(self._h).decode())

    def init_dense(self, name, value):
        v = np.ascontiguousarray(value, np.float32).ravel()
        self._ck(self._lib.pt_ps_init_dense(
            self._h, name.encode(),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), v.size),
            "init_dense")

    def push_dense(self, name, grad, optimize=True):
        g = np.ascontiguousarray(grad, np.float32).ravel()
        self._ck(self._lib.pt_ps_push_dense(
            self._h, name.encode(),
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), g.size,
            1 if optimize else 0), "push_dense")

    def pull_dense(self, name, shape):
        out = np.empty(int(np.prod(shape)), np.float32)
        self._ck(self._lib.pt_ps_pull_dense(
            self._h, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size),
            "pull_dense")
        return out.reshape(shape)

    def push_sparse(self, table, keys, grads):
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        grads = np.ascontiguousarray(grads, np.float32)
        dim = grads.shape[-1]
        grads = grads.reshape(keys.size, dim)
        self._ck(self._lib.pt_ps_push_sparse(
            self._h, table.encode(), dim,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), keys.size,
            grads.ctypes.data_as(ctypes.POINTER(ctypes.c_float))),
            "push_sparse")

    def pull_dense_if_newer(self, name, shape, version, out=None):
        """Version-gated pull (the async PullDenseWorker delta path):
        returns (array_or_None, new_version) — None means the server's
        table has not advanced past `version`, so no payload moved.
        Pass a reusable `out` buffer to avoid per-poll allocation."""
        if out is None:
            out = np.empty(int(np.prod(shape)), np.float32)
        ver = ctypes.c_uint64(int(version))
        rc = self._lib.pt_ps_pull_dense_if_newer(
            self._h, name.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
            ctypes.byref(ver))
        if rc == 1:
            return None, ver.value
        self._ck(rc, "pull_dense_if_newer")
        return out.reshape(shape), ver.value

    def pull_sparse(self, table, keys, dim):
        keys = np.ascontiguousarray(keys, np.int64).ravel()
        out = np.empty((keys.size, dim), np.float32)
        self._ck(self._lib.pt_ps_pull_sparse(
            self._h, table.encode(), dim,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), keys.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))),
            "pull_sparse")
        return out

    def push_sparse_bf16(self, table, keys, grads_bf16):
        """bf16-wire push: grads arrive as an ml_dtypes.bfloat16 array
        (e.g. straight off a device readback) and ship WITHOUT a host
        widen — the server widens while applying (bit-identical to the
        host astype it replaces) and the loopback RPC carries half the
        bytes."""
        import ml_dtypes

        keys = np.ascontiguousarray(keys, np.int64).ravel()
        g = np.ascontiguousarray(grads_bf16)
        if g.dtype != np.dtype(ml_dtypes.bfloat16):
            g = g.astype(ml_dtypes.bfloat16)
        dim = g.shape[-1]
        g16 = g.reshape(keys.size, dim).view(np.uint16)
        self._ck(self._lib.pt_ps_push_sparse_bf16(
            self._h, table.encode(), dim,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), keys.size,
            g16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))),
            "push_sparse_bf16")

    def pull_sparse_bf16(self, table, keys, dim, out=None):
        """bf16-wire pull: the server narrows fp32 rows to bf16
        (round-to-nearest-even, matching numpy astype) before the RPC;
        the result lands directly in `out` (or a fresh bf16 array) with
        no host-side narrow pass. `out` may be any [n, dim] uint16 or
        bfloat16 buffer — e.g. a slice of a padded wire buffer."""
        import ml_dtypes

        keys = np.ascontiguousarray(keys, np.int64).ravel()
        bf16 = np.dtype(ml_dtypes.bfloat16)
        if out is None:
            out = np.empty((keys.size, dim), bf16)
        view = out.view(np.uint16) if out.dtype == bf16 else out
        if view.dtype != np.uint16:
            raise ValueError(
                f"pull_sparse_bf16 out must be bfloat16 or uint16, got "
                f"{out.dtype}")
        if not view.flags["C_CONTIGUOUS"]:
            raise ValueError("pull_sparse_bf16 needs a contiguous out")
        if view.size != keys.size * dim:
            raise ValueError(
                f"pull_sparse_bf16 out has {view.size} elements, needs "
                f"{keys.size * dim}")
        self._ck(self._lib.pt_ps_pull_sparse_bf16(
            self._h, table.encode(), dim,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), keys.size,
            view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))),
            "pull_sparse_bf16")
        return out if out.dtype == bf16 else out.view(bf16)

    def barrier(self, barrier_id=0):
        self._ck(self._lib.pt_ps_barrier(self._h, barrier_id), "barrier")

    def heartbeat(self, trainer_id):
        self._ck(self._lib.pt_ps_heartbeat(self._h, trainer_id),
                 "heartbeat")

    def save(self, path):
        """Server-side table snapshot to `path` (the server owns the IO;
        checkpoint_notify_op.cc:66 / recv_save_op.cc capability)."""
        self._ck(self._lib.pt_ps_save(self._h, str(path).encode()),
                 "save")

    def load(self, path):
        """Restore a kSave snapshot into the server's tables
        (large_scale_kv.h:762 load capability)."""
        self._ck(self._lib.pt_ps_load(self._h, str(path).encode()),
                 "load")

    def shutdown_server(self):
        self._lib.pt_ps_shutdown(self._h)

    def close(self):
        # free the native handle under the RPC lock: an in-flight RPC on
        # another thread finishes first, and any later one sees None
        # (use-after-free here segfaulted the whole process when an
        # async recv thread outlived Communicator.stop()'s join timeout)
        with self._mu:
            if self._h:
                self._lib.pt_ps_disconnect(self._h)
                self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _shard(name, nshards):
    # stable across processes (unlike Python's salted hash())
    h = 0
    for ch in name.encode():
        h = (h * 131 + ch) & 0x7FFFFFFF
    return h % nshards


class Communicator:
    """Trainer-side grad/param exchange (communicator.h hierarchy parity).

    modes:
      'sync'  — push grads + pull params inline every step;
      'async' — background send thread merges queued grads and sends;
                background recv thread refreshes params every
                `recv_interval` s (AsyncCommunicator + PullDenseWorker);
      'geo'   — trainer keeps local params; every `geo_k` steps pushes the
                param DELTA since last sync and pulls the global value
                (GeoCommunicator / GEO-SGD).
    """

    def __init__(self, endpoints, mode="sync", trainer_id=0,
                 recv_interval=0.05, geo_k=4, send_queue_size=8):
        self.mode = mode
        self.trainer_id = trainer_id
        self.clients = [PsClient(h, int(p)) for h, p in
                        (e.split(":") for e in endpoints)]
        self.geo_k = geo_k
        self._geo_base = {}   # name -> param at last sync
        self._geo_step = 0
        self._dense_shapes = {}
        self._running = False
        # bounded like the reference's send channel (communicator.h
        # send_queue_size): an unbounded queue lets a contended host
        # batch up dozens of STALE grads and apply them in one burst —
        # async SGD diverges. push() blocks once the bound is hit.
        self.send_queue_size = max(int(send_queue_size), 1)
        self._send_q = []
        self._send_mu = threading.Lock()
        self._send_cv = threading.Condition(self._send_mu)
        self._send_error = None
        self._recv_interval = recv_interval
        self._latest = {}     # name -> freshly pulled param (async)
        self._latest_gen = 0  # bumps when recv_loop lands fresh data
        self._recv_error = None
        self._stop_evt = threading.Event()

    def _client_for(self, name):
        return self.clients[_shard(name, len(self.clients))]

    # ---------------- setup ----------------
    def init_params(self, named_params):
        """Trainer 0 pushes initial values; all trainers then barrier."""
        for name, val in named_params.items():
            self._dense_shapes[name] = tuple(np.shape(val))
            if self.trainer_id == 0:
                self._client_for(name).init_dense(name, val)
            if self.mode == "geo":
                self._geo_base[name] = np.array(val, np.float32)
        self.clients[0].barrier(0)

    # ---------------- sync/async dense path ----------------
    def push(self, named_grads):
        """Dense grads go to push_dense; SelectedRows grads (sparse
        embedding backward) go straight to push_sparse with their (rows,
        values) — never densified (parameter_send sparse path parity)."""
        from ...sparse import SelectedRows

        sparse = {n: g for n, g in named_grads.items()
                  if isinstance(g, SelectedRows)}
        dense = {n: g for n, g in named_grads.items() if n not in sparse}
        for name, g in sparse.items():
            # merge on the HOST: the rows are leaving for the pserver
            # anyway, and a device-side merge costs one accelerator
            # round-trip per eager op (prohibitive over remote links)
            rows = np.asarray(g.rows).ravel()
            vals = np.asarray(g.values).reshape(rows.size, -1)
            keep = rows < g.height  # drop shape-stable fill rows
            rows, vals = rows[keep], vals[keep]
            uniq, inv = np.unique(rows, return_inverse=True)
            merged = np.zeros((uniq.size, vals.shape[1]), vals.dtype)
            np.add.at(merged, inv, vals)
            self._client_for(name).push_sparse(name, uniq, merged)
        if not dense:
            return
        if self.mode == "async":
            with self._send_cv:
                while (self._running and self._send_error is None
                       and len(self._send_q) >= self.send_queue_size):
                    self._send_cv.wait(timeout=1.0)
                if self._send_error is not None:
                    raise RuntimeError(
                        "PS async send thread died") from self._send_error
                if self._running:
                    self._send_q.append(dict(dense))
                    return
            # communicator stopped (or never started): push inline so
            # the grad is neither lost nor parked on a dead queue
        for name, g in dense.items():
            self._client_for(name).push_dense(name, g)

    def pull(self, force=False):
        """force=True bypasses the async recv-thread cache and does a
        blocking dense pull from the servers (bounded-staleness
        fallback; sync mode always pulls)."""
        if self._recv_error is not None:
            raise RuntimeError(
                "PS async recv thread died") from self._recv_error
        shapes = list(self._dense_shapes.items())  # init_params may
        # grow the dict concurrently (engine pull thread vs first hook)
        if not force and self.mode == "async" and self._latest:
            return {n: self._latest[n].reshape(s)
                    for n, s in shapes if n in self._latest}
        return {n: self._client_for(n).pull_dense(n, s)
                for n, s in shapes}

    @property
    def latest_generation(self):
        """Bumps whenever the async recv thread lands genuinely fresh
        params; consumers can gate on it to tell a starved recv thread
        from a quiet server."""
        return self._latest_gen

    # ---------------- checkpoint ----------------
    def checkpoint_notify(self, dirname, load=False):
        """Notify every pserver to snapshot (or restore) its tables —
        the trainer-side checkpoint_notify_op role
        (operators/distributed_ops/checkpoint_notify_op.cc:66). Each
        shard writes `dirname/pserver_<i>.ptps`; the server process owns
        the file IO (recv_save_op semantics), so the path must be
        reachable from the pserver host. Returns the per-shard paths.

        In async mode the local send queue is flushed first so queued
        grads land in the snapshot. Multi-trainer jobs must quiesce the
        OTHER trainers themselves (e.g. `barrier()`) — trainer 0 then
        issues the notify, matching the reference's fleet save flow."""
        import os

        if not load and self.mode == "async":
            with self._send_mu:
                batch, self._send_q = self._send_q, []
            for d in batch:
                for n, g in d.items():
                    self._client_for(n).push_dense(n, g)
        paths = []
        for i, cl in enumerate(self.clients):
            p = os.path.join(str(dirname), f"pserver_{i}.ptps")
            (cl.load if load else cl.save)(p)
            paths.append(p)
        return paths

    # ---------------- geo path ----------------
    def geo_step(self, named_params):
        """Called every local step with current local params; returns
        possibly-updated params (after delta exchange every geo_k)."""
        self._geo_step += 1
        if self._geo_step % self.geo_k != 0:
            return named_params
        out = dict(named_params)
        for name, val in named_params.items():
            val = np.asarray(val, np.float32)
            delta = val - self._geo_base[name]
            c = self._client_for(name)
            c.push_dense(name, delta, optimize=False)  # server adds delta
            new = c.pull_dense(name, val.shape)
            self._geo_base[name] = new.copy()
            out[name] = new
        return out

    # ---------------- async workers ----------------
    def start(self):
        if self.mode != "async" or self._running:
            return
        self._running = True

        def send_loop():
            while not self._stop_evt.is_set():
                with self._send_cv:
                    batch, self._send_q = self._send_q, []
                    if batch:
                        self._send_cv.notify_all()
                if batch:
                    # merge grads for the same var (communicator merge_add)
                    try:
                        merged = {}
                        for d in batch:
                            for n, g in d.items():
                                g = np.asarray(g, np.float32)
                                merged[n] = merged.get(n, 0) + g
                        for n, g in merged.items():
                            self._client_for(n).push_dense(n, g)
                    except Exception as e:
                        # surface on the NEXT push(): with a bounded
                        # queue a silently-dead send thread would block
                        # the trainer forever
                        with self._send_cv:
                            self._send_error = e
                            self._send_cv.notify_all()
                        return
                else:
                    time.sleep(0.002)

        def recv_loop():
            consecutive_errs = 0
            versions = {}
            scratch = {}  # reusable per-name buffers (no per-poll alloc)
            while not self._stop_evt.is_set():
                try:
                    for n, s in list(self._dense_shapes.items()):
                        # delta gate: payload moves only when the server
                        # table advanced (PullDenseWorker without the
                        # full-param re-pull every interval)
                        if n not in scratch:
                            scratch[n] = np.empty(
                                int(np.prod(s)), np.float32)
                        arr, versions[n] = self._client_for(
                            n).pull_dense_if_newer(
                                n, s, versions.get(n, 0),
                                out=scratch[n])
                        if arr is not None:
                            self._latest[n] = arr.copy()
                            self._latest_gen += 1
                    consecutive_errs = 0
                except Exception as e:  # transient: retry, then surface
                    consecutive_errs += 1
                    if consecutive_errs >= 5:
                        self._recv_error = e
                        return
                time.sleep(self._recv_interval)

        self._threads = [threading.Thread(target=send_loop, daemon=True),
                         threading.Thread(target=recv_loop, daemon=True)]
        for t in self._threads:
            t.start()

    def stop(self):
        if not self._running:
            return
        self._stop_evt.set()
        for t in self._threads:
            t.join(timeout=2.0)
        # flip state, release blocked pushers, and drain in ONE critical
        # section: a waiter waking after a separate flush would append to
        # a never-drained queue and lose its grad (late push() calls now
        # go inline — see push())
        with self._send_cv:
            self._running = False
            batch, self._send_q = self._send_q, []
            self._send_cv.notify_all()
        for d in batch:
            for n, g in d.items():
                self._client_for(n).push_dense(n, g)

    def barrier(self, bid=1):
        self.clients[0].barrier(bid)

    def close(self):
        self.stop()
        for c in self.clients:
            c.close()


class DistributedLookupTable:
    """Sparse embedding on pserver hosts (distributed_lookup_table_op +
    large_scale_kv capability): pull rows for ids, push grads back.
    Rows init lazily server-side; host RAM holds the table, the TPU only
    sees the dense gathered minibatch."""

    def __init__(self, comm: Communicator, table_name, dim):
        self.comm = comm
        self.table = table_name
        self.dim = dim

    def lookup(self, ids):
        ids = np.asarray(ids, np.int64)
        flat = ids.ravel()
        rows = self.comm._client_for(self.table).pull_sparse(
            self.table, flat, self.dim)
        return rows.reshape(ids.shape + (self.dim,))

    def push_grad(self, ids, grads):
        ids = np.asarray(ids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(ids.size, self.dim)
        self.comm._client_for(self.table).push_sparse(self.table, ids,
                                                      grads)


def run_pserver(port=0, trainers=1, optimizer="sgd", lr=0.01,
                ready_file=None, block=True):
    """Pserver main loop (listen_and_serv_op capability;
    `python -m paddle_tpu.distributed.ps` entry)."""
    server = PsServer(port=port, trainers=trainers, optimizer=optimizer,
                      lr=lr)
    if ready_file:
        with open(ready_file, "w") as f:
            f.write(str(server.port))
    if not block:
        return server
    try:
        # exit when a trainer sends shutdown_server (listen_and_serv
        # semantics: server loop ends on the RPC shutdown notify)
        while not server.shutdown_requested():
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


class SparsePrefetcher:
    """Overlap sparse pulls with device compute (parameter_prefetch.cc
    capability): while the chip runs step t, a background thread pulls
    the embedding rows for step t+1's ids.

    usage:
        pf = SparsePrefetcher(comm, "emb", dim)
        pf.prime(first_ids)
        for batch in data:
            rows = pf.get()            # rows for current ids
            pf.prefetch(next_ids)      # overlap next pull with compute
            ... train on rows ...
    """

    def __init__(self, comm, table, dim, to_device=False):
        """to_device: issue the host→device transfer on the prefetch
        thread too, so by get() time the rows are already (or becoming)
        device-resident and the jitted step never blocks on H2D — the
        buffered_reader.cc overlap applied to PS pulls."""
        self._table = DistributedLookupTable(comm, table, dim)
        self._pending = None
        self._to_device = to_device

    def _pull(self, ids, aux=None):
        rows = self._table.lookup(ids)
        if self._to_device:
            import jax

            if aux is not None:
                return jax.device_put((rows, aux))
            rows = jax.device_put(rows)
        return rows if aux is None else (rows, aux)

    def prime(self, ids):
        self.prefetch(ids)

    def prefetch(self, ids, aux=None):
        """aux: optional host array shipped to the device on the
        prefetch thread alongside the rows (e.g. the chunk's labels) so
        the training dispatch never pays their H2D inline — folded into
        the SAME device_put as the rows, so it adds bytes but no extra
        fixed-latency tunnel call. When given, get() returns the pull
        result with the device aux appended."""
        import concurrent.futures

        if not hasattr(self, "_pool"):
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="pt-sparse-prefetch")
        if aux is None:
            self._pending = self._pool.submit(self._pull, ids)
        else:
            self._pending = self._pool.submit(self._pull, ids, aux)

    def get(self, timeout=60.0):
        if self._pending is None:
            raise RuntimeError("prefetch()/prime() before get()")
        out = self._pending.result(timeout=timeout)
        self._pending = None
        return out

    def close(self):
        # drain any in-flight pull BEFORE the caller tears the
        # communicator/native client down under the worker thread
        if self._pending is not None:
            try:
                self._pending.result(timeout=10.0)
            except Exception:
                pass
            self._pending = None
        if hasattr(self, "_pool"):
            # best effort: a pull stuck on a dead pserver must not hang
            # the caller's teardown forever
            self._pool.shutdown(wait=False)


class MergedSparseStream(SparsePrefetcher):
    """K-step merged sparse pull/push for async PS training over a
    high-latency device link.

    The reference AsyncCommunicator merges several batches' grads per
    send (communicator.h:253, `max_merge_var_num`); on a TPU host the
    same batching must also apply to the *device* transfers, whose fixed
    dispatch latency dwarfs per-batch payloads. The pull side is
    SparsePrefetcher's (one background worker, prefetch/get protocol)
    with a wire-dtype narrowing added: embedding rows for K training
    batches ship host→device as ONE transfer (bfloat16 on the wire —
    half the bytes; the pserver table stays fp32). The added push side
    reads the K per-step gradients back as ONE device→host readback,
    merged by row id before the pserver push.

    Staleness is bounded by K merged batches plus one prefetched chunk
    plus `max_pending` queued pushes — the same bounded-staleness regime
    the reference async PS mode already accepts.

    usage (ids chunk shaped [K, B, S]):
        ms = MergedSparseStream(comm, "emb", dim, height=VOCAB)
        ms.prime(ids0)
        for chunk in chunks:
            rows = ms.get()              # device [K,B,S,dim] wire dtype
            ms.prefetch(next_chunk)      # overlap next pull + H2D
            grads = train_k_steps(rows)  # one jitted lax.scan
            ms.push_async(chunk_ids, grads)  # one D2H + merged push
        ms.drain()                       # grads all applied at the PS

    unique_wire=True moves the id dedup to the PULL side and the row
    merge onto the DEVICE: the prefetch thread np.unique's the chunk's
    ids, pulls only the unique rows from the pserver, and ships
    (rows[Upad,D] wire-dtype, inv[K,B,S] int32) — the training chunk
    gathers `rows[inv[k]]` per step, and the gradient w.r.t. the unique
    rows is the XLA-transposed scatter-add, i.e. the row merge runs on
    the chip for free. The push side then reads back one already-merged
    [Upad,D] gradient and RPCs it straight to the pserver — no host
    np.unique/np.add.at on the critical plane, and every byte on the
    tunnel and the PS wire is for a *unique* row (real CTR id streams
    are Zipfian, so dedup cuts far deeper than the uniform-draw worst
    case). U is padded up to a multiple of `pad_rows` (sentinel id ==
    height, zero rows) so jit sees a handful of bucket shapes instead
    of a fresh compile per chunk.
    """

    def __init__(self, comm, table, dim, height, wire_dtype="bfloat16",
                 to_device=True, max_pending=4, unique_wire=False,
                 pad_rows=16384):
        import concurrent.futures

        super().__init__(comm, table, dim, to_device=to_device)
        self._comm = comm
        self._name = table
        self._dim = dim
        self._height = height
        self._wire_dtype = wire_dtype
        self._unique_wire = bool(unique_wire)
        self._pad_rows = max(int(pad_rows), 1)
        self._max_pending = max(int(max_pending), 1)
        self._push_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="pt-merged-push")
        self._push_futs = []
        # cumulative worker-thread seconds (host-plane accounting: on a
        # single-core host these serialize against the device link)
        self.pull_seconds = 0.0
        self.push_seconds = 0.0
        self.chunks = 0

    def _wire_np_dtype(self):
        if not self._wire_dtype or self._wire_dtype == "float32":
            return np.dtype(np.float32)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, self._wire_dtype,
                                self._wire_dtype))

    # ---------------- pull side (SparsePrefetcher + wire narrowing) ----
    def _pull(self, ids, aux=None):
        if self._unique_wire:
            return self._pull_unique(ids, aux)
        t0 = time.perf_counter()
        rows = self._table.lookup(ids)      # one RPC for all K batches
        wire = self._wire_np_dtype()
        if rows.dtype != wire:
            rows = rows.astype(wire)
        if self._to_device:
            import jax

            if aux is not None:
                rows, aux = jax.device_put((rows, aux))
            else:
                rows = jax.device_put(rows)
        self.pull_seconds += time.perf_counter() - t0
        self.chunks += 1
        return rows if aux is None else (rows, aux)

    def _pull_unique(self, ids, aux=None):
        t0 = time.perf_counter()
        ids = np.asarray(ids, np.int64)
        uniq, inv = np.unique(ids.ravel(), return_inverse=True)
        upad = -(-uniq.size // self._pad_rows) * self._pad_rows
        rows = np.zeros((upad, self._dim), self._wire_np_dtype())
        # one RPC for the UNIQUE rows only. bf16 wire: the pserver
        # narrows server-side straight into the padded wire buffer —
        # half the loopback bytes and zero host narrow pass; other
        # dtypes narrow on assignment from the fp32 pull
        if self._bf16_wire():
            self._comm._client_for(self._name).pull_sparse_bf16(
                self._name, uniq, self._dim, out=rows[:uniq.size])
        else:
            rows[:uniq.size] = self._table.lookup(uniq)
        uniq_pad = np.full(upad, self._height, np.int64)
        uniq_pad[:uniq.size] = uniq
        inv = inv.reshape(ids.shape).astype(np.int32)
        if self._to_device:
            import jax

            # one device_put for rows + inv + aux: the tunnel charges a
            # fixed latency per call, so the labels ride along free
            if aux is not None:
                rows, inv, aux = jax.device_put((rows, inv, aux))
            else:
                rows, inv = jax.device_put((rows, inv))
        self.pull_seconds += time.perf_counter() - t0
        self.chunks += 1
        out = (rows, inv, uniq_pad)
        return out if aux is None else out + (aux,)

    def _bf16_wire(self):
        """True when the bf16-on-the-wire fast path applies end to end:
        bfloat16 wire dtype AND the native client (the pure-python test
        fakes don't speak the bf16 opcodes)."""
        if self._wire_dtype != "bfloat16":
            return False
        cli = self._comm._client_for(self._name)
        return hasattr(cli, "push_sparse_bf16")

    # ---------------- push side ----------------
    def _push(self, ids, grads):
        from ...sparse import SelectedRows

        t0 = time.perf_counter()
        # np.asarray = the ONE device→host readback for K batches
        vals = np.asarray(grads).reshape(ids.size, self._dim)
        if self._unique_wire:
            # rows arrived pre-merged from the device scatter-add —
            # drop the pad sentinels and RPC straight to the pserver,
            # skipping Communicator.push's host unique/add.at plane
            flat = ids.ravel()
            keep = flat < self._height
            cli = self._comm._client_for(self._name)
            if self._bf16_wire() and vals.dtype == self._wire_np_dtype():
                # device readback is already bf16: ship it verbatim,
                # the server widens (bit-identical to a host astype)
                cli.push_sparse_bf16(self._name, flat[keep], vals[keep])
            else:
                if vals.dtype != np.float32:
                    vals = vals.astype(np.float32)
                cli.push_sparse(self._name, flat[keep], vals[keep])
        else:
            if vals.dtype != np.float32:
                vals = vals.astype(np.float32)
            self._comm.push({self._name: SelectedRows(ids.ravel(), vals,
                                                      self._height)})
        self.push_seconds += time.perf_counter() - t0

    def push_async(self, ids, grads):
        # backpressure: never hold more than max_pending grad chunks
        # (each pins a [K,B,S,D] device array) — block on the oldest
        while len(self._push_futs) >= self._max_pending:
            self._push_futs.pop(0).result()
        # surface completed-worker exceptions; pop BEFORE result() so a
        # failed push raises once, not on every later call
        while self._push_futs and self._push_futs[0].done():
            self._push_futs.pop(0).result()
        self._push_futs.append(self._push_pool.submit(
            self._push, np.asarray(ids, np.int64), grads))

    def drain(self, timeout=300.0):
        """Block until every pushed grad chunk is applied at the PS."""
        while self._push_futs:
            self._push_futs.pop(0).result(timeout=timeout)

    def close(self):
        try:
            self.drain(timeout=10.0)
        except Exception:
            pass
        self._push_pool.shutdown(wait=False)
        super().close()
