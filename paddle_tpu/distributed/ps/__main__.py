"""`python -m paddle_tpu.distributed.ps --port P --trainers N` — standalone
pserver process (fleet `run_server` / listen_and_serv entry)."""
import argparse

from . import run_pserver


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--trainers", type=int, default=1)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--ready-file", default=None)
    args = ap.parse_args()
    run_pserver(port=args.port, trainers=args.trainers,
                optimizer=args.optimizer, lr=args.lr,
                ready_file=args.ready_file)


if __name__ == "__main__":
    main()
