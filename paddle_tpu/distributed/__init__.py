"""paddle.distributed: collectives + launch + fleet.

Reference parity: python/paddle/distributed/ (collective.py eager
collectives, fleet/, launch.py, spawn.py). TPU-native design: process model
is jax multi-controller (jax.distributed.initialize over DCN); in-program
collectives are XLA ops over ICI via shard_map (paddle_tpu.parallel). Eager
`all_reduce` on a 1-process mesh is the identity, matching a 1-rank NCCL
group; under multi-process it runs a psum across processes via a global
device mesh.
"""
from __future__ import annotations

import os

import numpy as np

from ..core.tensor import Tensor

_initialized = [False]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


def init_parallel_env():
    """dygraph collective bootstrap (reference: NCCLParallelContext
    imperative/nccl_context.h:61 → jax.distributed.initialize)."""
    if _initialized[0]:
        return
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if world > 1:
        import jax

        coord = os.environ.get("PADDLE_MASTER",
                               os.environ.get("MASTER_ADDR", "127.0.0.1")
                               + ":" +
                               os.environ.get("MASTER_PORT", "8701"))
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=world, process_id=rank)
    _initialized[0] = True


def get_rank():
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))


def get_world_size():
    try:
        import jax

        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))


def _psum_all_devices(arr, op="sum"):
    """Cross-device reduction over ALL visible devices via shard_map."""
    import jax

    if len(jax.devices()) == 1 and jax.process_count() == 1:
        return arr
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("x",))

    red = {"sum": jax.lax.psum, "max": jax.lax.pmax,
           "min": jax.lax.pmin}[op]

    @jax.jit
    def f(a):
        return shard_map(lambda v: red(v, "x"), mesh=mesh,
                         in_specs=P(), out_specs=P())(a)

    return f(arr)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    opname = {ReduceOp.SUM: "sum", ReduceOp.MAX: "max",
              ReduceOp.MIN: "min"}.get(op, "sum")
    tensor._data = _psum_all_devices(tensor._data, opname)
    return tensor


def broadcast(tensor, src, group=None, sync_op=True):
    # single-controller: all ranks already see src's value
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    world = get_world_size()
    for _ in range(world):
        tensor_list.append(tensor.clone())
    return tensor_list


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        tensor.set_value(tensor_list[get_rank()])
    return tensor


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def barrier(group=None):
    import jax

    # device-level sync; multi-process barrier via a tiny psum
    if get_world_size() > 1:
        _psum_all_devices(jax.numpy.zeros((1,)))


def wait(tensor, group=None, use_calc_stream=True):
    tensor._data.block_until_ready()


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity: fork worker processes."""
    import multiprocessing as mp

    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


def _spawn_entry(func, args, env):
    os.environ.update(env)
    func(*args)


from . import fleet  # noqa: F401,E402
from .parallel import DataParallel  # noqa: F401,E402
from . import collective  # noqa: F401,E402


def all_reduce_mean_tree(named_arrays):
    """Average a dict of raw arrays across data-parallel replicas
    (LocalSGD periodic sync; transpiler/collective.py:270 capability).
    Single-replica worlds return the input unchanged."""
    world = get_world_size()
    if world <= 1:
        return named_arrays
    return {n: _psum_all_devices(v) / world
            for n, v in named_arrays.items()}
