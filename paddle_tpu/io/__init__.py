"""paddle.io: Dataset/DataLoader/Sampler parity
(python/paddle/fluid/dataloader/{dataset,batch_sampler,dataloader_iter}.py
and fluid/reader.py:123 DataLoader). TPU-native: worker threads + a bounded
prefetch queue feeding host numpy batches; device transfer happens at op
dispatch (XLA owns HBM). Multiprocess workers use the C++ blocking queue
backend when built (csrc/feed).
"""
from __future__ import annotations

import itertools
import math

import numpy as np

from ..core import random as _random
from ..core.tensor import Tensor
from ..testing import faults
from .dataloader_iter import (MultiprocessIter, ThreadPrefetcher,  # noqa: F401
                              WorkerInfo)
from .serialization import load, save  # noqa: F401

_PT_DL_NEXT = faults.point("dataloader.next")


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise TypeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise TypeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            v = d[idx]
            out.extend(v if isinstance(v, (list, tuple)) else [v])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("lengths must sum to dataset size")
    perm = np.random.permutation(total)
    out = []
    ofs = 0
    for n in lengths:
        out.append(Subset(dataset, perm[ofs:ofs + n].tolist()))
        ofs += n
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num = num_samples

    @property
    def num_samples(self):
        return self._num if self._num is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        return iter(np.random.choice(
            len(self.weights), self.num_samples, self.replacement,
            p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """fluid/dataloader/batch_sampler.py DistributedBatchSampler parity:
    shards the dataset across ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_rank, get_world_size

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b._data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    return batch


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_multiprocess=True):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._use_mp = use_multiprocess and use_shared_memory
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    @staticmethod
    def from_generator(*args, **kwargs):
        """Static-graph feeding front door (fluid/reader.py:409);
        delegates to the single factory in fluid.io.DataLoader."""
        from ..fluid.io import DataLoader as _FluidDataLoader

        return _FluidDataLoader.from_generator(*args, **kwargs)

    def _batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def __iter__(self):
        if self.num_workers <= 0:
            for b in self._batches():
                yield _to_tensors(_PT_DL_NEXT(payload=b))
            return
        if self._use_mp:
            it = MultiprocessIter(
                self.dataset,
                None if self._iterable_mode else self.batch_sampler,
                self.collate_fn, self.num_workers,
                prefetch_factor=self.prefetch,
                worker_init_fn=self.worker_init_fn, timeout=self.timeout,
                iterable=self._iterable_mode,
                batch_size=self.batch_size if self._iterable_mode else 1,
                drop_last=self.drop_last if self._iterable_mode else False)
            try:
                for b in it:
                    yield _to_tensors(_PT_DL_NEXT(payload=b))
            finally:
                it.shutdown()
            return
        # threaded prefetch pipeline (the buffered_reader.cc equivalent)
        for b in ThreadPrefetcher(
                self._batches(),
                depth=self.prefetch * max(1, self.num_workers)):
            yield _to_tensors(_PT_DL_NEXT(payload=b))


def _to_tensors(batch):
    if isinstance(batch, tuple):
        return tuple(_to_tensors(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _to_tensors(v) for k, v in batch.items()}
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    return batch


def get_worker_info():
    from .dataloader_iter import get_worker_info as _gwi

    return _gwi()


class DevicePrefetcher:
    """Host→device double-buffered prefetch (reference:
    operators/reader/buffered_reader.cc:1 — the buffered reader that
    overlaps H2D copies with compute).

    Wraps any iterator of numpy/jax pytrees. A background thread pulls
    host batches and issues async `jax.device_put`s `depth` ahead, so by
    the time the training step asks for batch k its transfer has been in
    flight while step k-1 computed. Yields device-committed pytrees.
    """

    _END = object()

    def __init__(self, it, sharding=None, depth=2):
        import queue
        import threading

        self._q = queue.Queue(maxsize=max(1, depth))
        self._err = None
        self._closed = False

        def _put(item):
            # blocking put that aborts promptly once close() is called;
            # the END sentinel MUST go through here too — dropping it on
            # a full queue would strand the consumer in get() forever
            while not self._closed:
                try:
                    self._q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def _pump():
            import jax

            try:
                for batch in it:
                    if self._closed:
                        return
                    put = (lambda a: jax.device_put(a, sharding)) \
                        if sharding is not None else jax.device_put

                    def place(a):
                        if isinstance(a, jax.Array):
                            # already device-resident: device_put moves/
                            # reshards WITHOUT a host round-trip
                            return put(a)
                        if isinstance(a, np.ndarray) or np.isscalar(a) \
                                or hasattr(a, "__array__"):
                            return put(np.asarray(a))
                        return a

                    _put(jax.tree_util.tree_map(place, batch))
            except BaseException as e:  # surfaced on the consumer side
                self._err = e
            finally:
                _put(self._END)

        self._thread = threading.Thread(target=_pump, daemon=True)
        self._thread.start()

    def close(self):
        """Stop the pump and release queued device buffers. Call when
        abandoning iteration early; iterating to exhaustion cleans up on
        its own."""
        self._closed = True
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        self._thread.join(timeout=5)

    def __iter__(self):
        try:
            while True:
                item = self._q.get()
                if item is self._END:
                    if self._err is not None:
                        raise self._err
                    return
                yield item
        finally:
            self.close()
