"""Crash-safe + async + sharded checkpointing.

Two backends:

  * `CheckpointManager` — the dependency-free crash-safe store the
    training/serving stack builds on. Every step is a directory of
    shards (one per top-level state key) published ATOMICALLY: shards
    + a manifest with per-shard CRC32 checksums are written into a
    `_tmp.*` staging dir, fsynced, then `os.rename`d into place — a
    crash at ANY byte leaves either the complete previous step or an
    ignorable staging dir, never a torn checkpoint. `restore()`
    validates checksums and falls back to the newest VALID step,
    flagging what it skipped (`last_restore_report`); `async_save=True`
    snapshots state on the caller thread and writes in the background,
    with any background error re-raised on `wait()` / the next
    `save()` — never lost. Instrumented with the `checkpoint.write` /
    `checkpoint.read` fault points (testing/faults.py) so torn-write
    and corrupt-shard recovery is deterministically testable.

  * `AsyncCheckpointer` — the orbax-backed sharded form SURVEY §5.4
    prescribes (pjit arrays restore with shardings intact).

API:
    mgr = CheckpointManager(dir, max_to_keep=3, async_save=True)
    mgr.save(step, {"model": model.state_dict(), "opt": ...})
    mgr.wait()                      # barrier; raises background errors
    state = mgr.restore()           # newest VALID step
    steps = mgr.all_steps()
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import threading
import warnings
import zlib

import numpy as np

from ..testing import faults
from .serialization import _pack, _unpack

_PT_WRITE = faults.point("checkpoint.write")
_PT_READ = faults.point("checkpoint.read")


class CheckpointError(RuntimeError):
    """Checkpoint IO failed."""


class CheckpointCorrupt(CheckpointError):
    """A step failed validation (missing/unreadable manifest, missing
    shard, size or CRC32 mismatch)."""


_STEP_PREFIX = "step_"
_TMP_PREFIX = "_tmp."
_MANIFEST = "manifest.json"


class CheckpointManager:
    """Atomic, checksummed, retained checkpoint directory.

    Layout (one dir per step, manifest written last, dir renamed into
    place as the commit point):

        <dir>/step_00000012/
            shard_0000.bin        # pickle of the packed subtree
            ...
            manifest.json         # {"step", "shards": {key: {file,
                                  #   crc32, size}}, "wrapped"}

    `max_to_keep` prunes the oldest finalized steps after each
    successful save (and sweeps stale `_tmp.*` staging dirs left by
    crashes). Not safe for concurrent writers on one directory; any
    number of readers is fine."""

    def __init__(self, directory, *, max_to_keep=3, async_save=False):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.max_to_keep = None if max_to_keep is None else \
            int(max_to_keep)
        self.async_save = bool(async_save)
        self._pending = None
        self._async_error = None
        #: report of the last fallback restore: {"step", "skipped"}
        self.last_restore_report = None

    # ---- paths ----
    def _step_dir(self, step):
        return os.path.join(self._dir, f"{_STEP_PREFIX}{int(step):08d}")

    def all_steps(self):
        """Every finalized (renamed-into-place) step, sorted — validity
        is checked lazily by `restore()`/`validate()`."""
        out = []
        for name in os.listdir(self._dir):
            if name.startswith(_STEP_PREFIX):
                try:
                    out.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
        return sorted(out)

    def valid_steps(self):
        return [s for s in self.all_steps() if self.validate(s) is None]

    def latest_step(self, valid_only=True):
        steps = self.valid_steps() if valid_only else self.all_steps()
        return steps[-1] if steps else None

    # ---- save ----
    def save(self, step, state, *, force=False):
        """Checkpoint `state` (any pytree of Tensors/arrays/host data)
        as `step`. Sync mode blocks until the step is durably
        published. Async mode snapshots the tree to host memory NOW and
        returns; the write happens on a background thread and any
        failure surfaces on `wait()` or the next `save()`."""
        self.wait()          # serialize saves; surfaces prior errors
        tree = _pack(state)  # host snapshot, device-independent
        if not self.async_save:
            self._write(int(step), tree, force)
            return
        t = threading.Thread(
            target=self._write_guarded, args=(int(step), tree, force),
            name="paddle-tpu-ckpt-save", daemon=True)
        self._pending = t
        t.start()

    def _write_guarded(self, step, tree, force):
        try:
            self._write(step, tree, force)
        except BaseException as e:   # surfaced on wait()/next save
            self._async_error = e

    def wait(self):
        """Barrier for an in-flight async save; re-raises any error the
        background write hit (a failed checkpoint must never be
        silently dropped)."""
        t, self._pending = self._pending, None
        if t is not None:
            t.join()
        e, self._async_error = self._async_error, None
        if e is not None:
            raise e

    def _write(self, step, tree, force):
        final = self._step_dir(step)
        if os.path.exists(final):
            if not force:
                raise CheckpointError(
                    f"step {step} already exists at {final!r} "
                    f"(pass force=True to overwrite)")
            shutil.rmtree(final)
        tmp = os.path.join(self._dir,
                           _TMP_PREFIX + os.path.basename(final))
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            wrapped = not isinstance(tree, dict) or not tree
            shards = {"state": tree} if wrapped else tree
            manifest = {"format": 1, "step": step, "wrapped": wrapped,
                        "shards": {}}
            for i, (key, sub) in enumerate(shards.items()):
                fname = f"shard_{i:04d}.bin"
                buf = pickle.dumps(sub, protocol=4)
                crc = zlib.crc32(buf) & 0xFFFFFFFF
                size = len(buf)
                # fault point: raise = crash mid-save (staging dir is
                # all that's left), corrupt = torn bytes the manifest
                # checksum will catch on restore
                buf = _PT_WRITE(payload=buf)
                with open(os.path.join(tmp, fname), "wb") as f:
                    f.write(buf)
                    f.flush()
                    os.fsync(f.fileno())
                manifest["shards"][str(key)] = {
                    "file": fname, "crc32": crc, "size": size}
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, final)   # the atomic commit point
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune(keep=step)

    def _prune(self, keep):
        for name in os.listdir(self._dir):
            if name.startswith(_TMP_PREFIX):   # stale staging dirs
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)
        if self.max_to_keep is None:
            return
        steps = self.all_steps()
        for s in steps[:max(0, len(steps) - self.max_to_keep)]:
            if s != keep:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # ---- restore ----
    def validate(self, step):
        """None when the step is intact, else the reason string (no
        exception: callers decide whether a bad step is fatal)."""
        try:
            self._read(step)
        except CheckpointError as e:
            return str(e)
        return None

    def _read(self, step):
        d = self._step_dir(step)
        mpath = os.path.join(d, _MANIFEST)
        if not os.path.isdir(d):
            raise CheckpointError(f"step {step}: no such checkpoint")
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorrupt(
                f"step {step}: missing/unreadable manifest ({e})")
        shards = {}
        for key, meta in manifest.get("shards", {}).items():
            fpath = os.path.join(d, meta["file"])
            try:
                with open(fpath, "rb") as f:
                    buf = f.read()
            except OSError as e:
                raise CheckpointCorrupt(
                    f"step {step}: shard {key!r} unreadable ({e})")
            buf = _PT_READ(payload=buf)   # fault point: read-side rot
            if len(buf) != meta["size"] or \
                    (zlib.crc32(buf) & 0xFFFFFFFF) != meta["crc32"]:
                raise CheckpointCorrupt(
                    f"step {step}: shard {key!r} failed checksum "
                    f"(torn or corrupt write)")
            shards[key] = pickle.loads(buf)
        if manifest.get("wrapped"):
            return shards["state"]
        return shards

    def restore(self, step=None, *, return_numpy=False):
        """Load a checkpoint. With an explicit `step`, corruption is an
        error (`CheckpointCorrupt`). With `step=None`, walks steps
        newest-first, SKIPS corrupt/torn ones (flagged via a warning +
        `last_restore_report`), and returns the newest valid state —
        the crash-recovery path."""
        if step is not None:
            return _unpack(self._read(int(step)), return_numpy)
        skipped = []
        for s in reversed(self.all_steps()):
            try:
                tree = self._read(s)
            except CheckpointError as e:
                skipped.append((s, str(e)))
                continue
            self.last_restore_report = {"step": s, "skipped": skipped}
            if skipped:
                warnings.warn(
                    f"checkpoint restore fell back to step {s}; "
                    f"skipped corrupt step(s) "
                    f"{[x[0] for x in skipped]}")
            return _unpack(tree, return_numpy)
        self.last_restore_report = {"step": None, "skipped": skipped}
        raise FileNotFoundError(
            f"no valid checkpoints under {self._dir!r}"
            + (f" (skipped corrupt: {[x[0] for x in skipped]})"
               if skipped else ""))

    # ---- lifecycle ----
    def close(self):
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.wait()
        return False


def _to_tree(obj):
    """paddle state_dict (name -> Tensor/ndarray) -> pure array pytree."""
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        return np.asarray(obj._data) if obj._data.dtype.name != \
            "bfloat16" else obj._data
    if isinstance(obj, dict):
        return {k: _to_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_tree(v) for v in obj]
    return obj


class AsyncCheckpointer:
    """Orbax-backed async checkpoint manager (save_persistables +
    auto-checkpoint capability with background IO)."""

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True))

    def save(self, step, state, force=False):
        """Non-blocking: returns once the device buffers are snapshotted;
        serialization continues in the background."""
        import orbax.checkpoint as ocp

        tree = _to_tree(state)
        self._mgr.save(int(step), args=ocp.args.StandardSave(tree),
                       force=force)

    def wait(self):
        self._mgr.wait_until_finished()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, step=None):
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self._dir!r}")
        return self._mgr.restore(int(step),
                                 args=ocp.args.StandardRestore())

    def close(self):
        self._mgr.close()


def save_sharded(state, directory):
    """One-shot sharded save: pjit/NamedSharding arrays keep their layout
    (each host writes its shards — multi-controller ready)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(_to_tree(state)),
               force=True)


def load_sharded(directory):
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    return ckptr.restore(os.path.abspath(directory))
