"""Async + sharded checkpointing over orbax.

Reference parity: the checkpoint/resume family (fluid/io.py
save_persistables, incubate auto-checkpoint) upgraded to the TPU-native
form SURVEY §5.4 prescribes: orbax-style async sharded checkpoints —
the save returns immediately while device arrays stream to disk on a
background thread, and sharded (pjit) arrays restore with their
shardings intact on load.

API:
    ck = AsyncCheckpointer(dir)
    ck.save(step, {"model": model.state_dict(), "opt": opt.state_dict()})
    ck.wait()                       # barrier (optional)
    state = ck.restore()            # latest step
    steps = ck.all_steps()
"""
from __future__ import annotations

import os

import numpy as np


def _to_tree(obj):
    """paddle state_dict (name -> Tensor/ndarray) -> pure array pytree."""
    from ..core.tensor import Tensor

    if isinstance(obj, Tensor):
        return np.asarray(obj._data) if obj._data.dtype.name != \
            "bfloat16" else obj._data
    if isinstance(obj, dict):
        return {k: _to_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_tree(v) for v in obj]
    return obj


class AsyncCheckpointer:
    """Orbax-backed async checkpoint manager (save_persistables +
    auto-checkpoint capability with background IO)."""

    def __init__(self, directory, max_to_keep=3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True))

    def save(self, step, state, force=False):
        """Non-blocking: returns once the device buffers are snapshotted;
        serialization continues in the background."""
        import orbax.checkpoint as ocp

        tree = _to_tree(state)
        self._mgr.save(int(step), args=ocp.args.StandardSave(tree),
                       force=force)

    def wait(self):
        self._mgr.wait_until_finished()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def latest_step(self):
        return self._mgr.latest_step()

    def restore(self, step=None):
        import orbax.checkpoint as ocp

        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints under {self._dir!r}")
        return self._mgr.restore(int(step),
                                 args=ocp.args.StandardRestore())

    def close(self):
        self._mgr.close()


def save_sharded(state, directory):
    """One-shot sharded save: pjit/NamedSharding arrays keep their layout
    (each host writes its shards — multi-controller ready)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(directory)
    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    ckptr.save(path, args=ocp.args.StandardSave(_to_tree(state)),
               force=True)


def load_sharded(directory):
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.StandardCheckpointHandler())
    return ckptr.restore(os.path.abspath(directory))
