"""Model encryption (framework/io/crypto + pybind/crypto.cc parity).

Native AES-128-CTR with an integrity tag (csrc/ptcore/crypto.cc);
encrypt/decrypt inference artifacts at rest:

    from paddle_tpu.io import crypto
    c = crypto.CipherFactory.create_cipher()
    c.encrypt_to_file(key, model_path, enc_path)
    c.decrypt_from_file(key, enc_path, model_path)
"""
from __future__ import annotations

import os

from ..core.native import load_library


def encrypt_file(src, dst, key):
    lib = load_library(required=True)
    rc = lib.pt_cipher_encrypt_file(
        os.fspath(src).encode(), os.fspath(dst).encode(),
        key.encode() if isinstance(key, str) else key)
    if rc != 0:
        raise IOError(f"encrypt_file({src!r}) failed rc={rc}")


def decrypt_file(src, dst, key):
    lib = load_library(required=True)
    rc = lib.pt_cipher_decrypt_file(
        os.fspath(src).encode(), os.fspath(dst).encode(),
        key.encode() if isinstance(key, str) else key)
    if rc == -5:
        raise ValueError(
            f"decrypt_file({src!r}): wrong key or corrupted file "
            f"(integrity tag mismatch)")
    if rc != 0:
        raise IOError(f"decrypt_file({src!r}) failed rc={rc}")


def is_encrypted(path):
    lib = load_library(required=True)
    return bool(lib.pt_cipher_is_encrypted(os.fspath(path).encode()))


class Cipher:
    """pybind crypto.cc Cipher parity (file-level AES-CTR)."""

    def encrypt_to_file(self, key, src, dst):
        encrypt_file(src, dst, key)

    def decrypt_from_file(self, key, src, dst):
        decrypt_file(src, dst, key)


class CipherFactory:
    @staticmethod
    def create_cipher(config_file=None):
        return Cipher()


def encrypt_inference_model(model_dir, out_dir, key,
                            files=("__model__", "__params__")):
    """Encrypt a saved inference model directory (the reference's
    encrypted-model deployment flow)."""
    os.makedirs(out_dir, exist_ok=True)
    for f in files:
        src = os.path.join(model_dir, f)
        if os.path.exists(src):
            encrypt_file(src, os.path.join(out_dir, f), key)


def decrypt_inference_model(enc_dir, out_dir, key,
                            files=("__model__", "__params__")):
    os.makedirs(out_dir, exist_ok=True)
    for f in files:
        src = os.path.join(enc_dir, f)
        if os.path.exists(src):
            decrypt_file(src, os.path.join(out_dir, f), key)
