"""Multiprocess DataLoader workers.

Reference parity: python/paddle/fluid/dataloader/dataloader_iter.py
(_DataLoaderIterMultiProcess) + fluid/multiprocess_utils.py — worker
subprocesses pull index batches from an index queue, collate samples, and
push numpy batches back through a result queue. TPU-native notes: batches
stay host-side numpy (XLA owns HBM; transfer happens at dispatch), and
ordering is preserved by reordering out-of-order results, like the
reference's _task_infos bookkeeping.
"""
from __future__ import annotations

import atexit
import itertools
import multiprocessing as mp
import queue
import threading
import weakref

# one process-level hook; iterators register into a weak set so per-epoch
# iterators are collectable (atexit must not pin them)
_live_iters = weakref.WeakSet()


def _shutdown_all():
    for it in list(_live_iters):
        try:
            it.shutdown()
        except Exception:
            pass


atexit.register(_shutdown_all)


class WorkerInfo:
    """fluid/dataloader/worker.py WorkerInfo equivalent."""

    def __init__(self, id, num_workers, dataset, seed):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers}, "
                f"seed={self.seed})")


_worker_info = None


def get_worker_info():
    """Inside a worker process returns its WorkerInfo, else None
    (paddle.io.get_worker_info parity)."""
    return _worker_info


def _worker_loop(dataset, index_queue, result_queue, collate_fn, wid,
                 num_workers, seed, worker_init_fn, iterable, drop_last):
    global _worker_info
    import numpy as np

    np.random.seed((seed + wid) % (2**32))
    _worker_info = WorkerInfo(wid, num_workers, dataset, seed + wid)
    if worker_init_fn is not None:
        try:
            worker_init_fn(wid)
        except Exception as e:
            result_queue.put(("init_error", None, e))
            return
    # Reference semantics (fluid/dataloader/worker.py): each worker sees the
    # FULL IterableDataset stream; the dataset shards itself via
    # get_worker_info() if it wants disjoint data.
    stream = iter(dataset) if iterable else None
    while True:
        try:
            task = index_queue.get()
        except (EOFError, OSError):
            return
        if task is None:
            return
        task_id, indices = task
        try:
            if iterable:
                samples = list(itertools.islice(stream, len(indices)))
                if not samples or (drop_last and
                                   len(samples) < len(indices)):
                    result_queue.put((task_id, None, StopIteration()))
                    continue
                batch = collate_fn(samples)
            else:
                batch = collate_fn([dataset[i] for i in indices])
            result_queue.put((task_id, batch, None))
        except Exception as e:  # ship the error to the parent
            result_queue.put((task_id, None, e))


class MultiprocessIter:
    """One epoch of multiprocess loading. Preserves batch order."""

    def __init__(self, dataset, batches, collate_fn, num_workers,
                 prefetch_factor=2, worker_init_fn=None, timeout=0,
                 iterable=False, batch_size=1, seed=0, drop_last=False):
        # spawn-family start methods only: fork would duplicate JAX's
        # runtime threads into the worker (deadlock risk — the reference
        # hit the same with CUDA, multiprocess_utils.py). forkserver
        # amortises interpreter startup; PT_DATALOADER_START_METHOD
        # overrides for debugging.
        import os as _os

        method = _os.environ.get("PT_DATALOADER_START_METHOD")
        if method is None:
            method = "forkserver" if "forkserver" in \
                mp.get_all_start_methods() else "spawn"
        self._ctx = mp.get_context(method)
        self._result_queue = self._ctx.Queue()
        self._workers = []
        self._index_queues = []
        self._timeout = timeout or None
        self._iterable = iterable
        self._num_workers = num_workers
        # pending batches of indices (index-mode) or dummy slices (iterable)
        if iterable:
            self._batches = iter(lambda: list(range(batch_size)), None)
        else:
            self._batches = iter(batches)
        self._next_task = 0        # next task id to hand out
        self._next_yield = 0       # next task id to yield (ordering)
        self._cache = {}
        # iterable mode: workers that answered StopIteration once; they are
        # skipped by the dispatcher and counted at most once toward epoch end
        self._exhausted = set()
        self._task_worker = {}     # task id -> wid it was dispatched to
        self._rr = 0               # round-robin cursor over live workers
        self._sent = 0
        self._outstanding_target = num_workers * max(2, prefetch_factor)
        for wid in range(num_workers):
            iq = self._ctx.Queue()
            w = self._ctx.Process(
                target=_worker_loop,
                args=(dataset, iq, self._result_queue, collate_fn, wid,
                      num_workers, seed, worker_init_fn, iterable,
                      drop_last),
                daemon=True)
            w.start()
            self._workers.append(w)
            self._index_queues.append(iq)
        self._closed = False
        _live_iters.add(self)
        for _ in range(self._outstanding_target):
            if not self._dispatch_one():
                break

    def _dispatch_one(self):
        if len(self._exhausted) >= self._num_workers:
            return False
        try:
            indices = next(self._batches)
        except StopIteration:
            return False
        for _ in range(self._num_workers):
            wid = self._rr % self._num_workers
            self._rr += 1
            if wid not in self._exhausted:
                break
        self._task_worker[self._next_task] = wid
        self._index_queues[wid].put((self._next_task, indices))
        self._next_task += 1
        self._sent += 1
        return True

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._next_yield in self._cache:
                tid = self._next_yield
                batch, err = self._cache.pop(tid)
                self._next_yield += 1
                wid = self._task_worker.pop(tid, tid % self._num_workers)
                if isinstance(err, StopIteration):
                    # this iterable worker ran dry; count each worker once
                    # (in-flight tasks to an already-dry worker answer
                    # StopIteration too) and stop dispatching to it
                    self._exhausted.add(wid)
                    if len(self._exhausted) >= self._num_workers:
                        self.shutdown()
                        raise StopIteration
                    self._dispatch_one()  # keep remaining workers busy
                    continue
                if err is not None:
                    self.shutdown()
                    raise err
                self._dispatch_one()
                return batch
            if self._next_yield >= self._sent and not self._dispatch_one():
                self.shutdown()
                raise StopIteration
            try:
                task_id, batch, err = self._result_queue.get(
                    timeout=self._timeout)
            except queue.Empty:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader timed out after {self._timeout}s waiting "
                    "for worker batch")
            if task_id == "init_error":
                self.shutdown()
                raise RuntimeError(
                    "DataLoader worker_init_fn failed") from err
            self._cache[task_id] = (batch, err)

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for iq in self._index_queues:
            try:
                iq.put(None)
            except Exception:
                pass
        for w in self._workers:
            w.join(timeout=1.0)
            if w.is_alive():
                w.terminate()
        for iq in self._index_queues:
            try:
                iq.close()
            except Exception:
                pass
        try:
            self._result_queue.close()
        except Exception:
            pass

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class ThreadPrefetcher:
    """Bounded background prefetch thread — the buffered_reader.cc
    (operators/reader/buffered_reader.cc) double-buffer equivalent."""

    def __init__(self, gen, depth=2):
        self._q = queue.Queue(maxsize=max(1, depth))
        self._stop = object()
        self._err = None

        def run():
            try:
                for item in gen:
                    self._q.put(item)
            except Exception as e:
                self._err = e
            finally:
                self._q.put(self._stop)

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._stop:
                if self._err is not None:
                    raise self._err
                return
            yield item
