"""paddle.save / paddle.load.

Reference parity: fluid/dygraph/checkpoint.py (save_dygraph/load_dygraph) and
python/paddle/framework/io.py. Format: a pickle of nested containers where
tensors are stored as numpy arrays + dtype tag (bfloat16-safe)."""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.dtypes import bfloat16
from ..core.tensor import Tensor


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = obj.numpy()
        if arr.dtype.name == "bfloat16":
            return {"__tensor__": arr.astype(np.float32),
                    "__dtype__": "bfloat16", "__name__": obj.name}
        return {"__tensor__": arr, "__dtype__": arr.dtype.name,
                "__name__": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if "__tensor__" in obj:
            arr = obj["__tensor__"]
            if obj.get("__dtype__") == "bfloat16":
                import jax.numpy as jnp

                arr = jnp.asarray(arr, dtype=bfloat16)
            if return_numpy:
                return np.asarray(arr)
            t = Tensor(arr)
            t.name = obj.get("__name__", "")
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        data = pickle.load(f)
    return _unpack(data, return_numpy)
