"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if self.verbose and step % self.log_freq == 0:
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"Epoch {self.epoch} step {step}: {msg}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            msg = " - ".join(f"{k}: {_fmt(v)}"
                             for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s: {msg}")


def _fmt(v):
    if isinstance(v, (list, tuple)):
        return ", ".join(f"{float(x):.4f}" for x in np.ravel(v))
    try:
        return f"{float(v):.4f}"
    except (TypeError, ValueError):
        return str(v)


class ModelCheckpoint(Callback):
    """Epoch-end checkpointing through the crash-safe
    `io.checkpoint.CheckpointManager`: atomic tmp+rename publishes with
    per-shard checksums (a kill mid-save can never leave a torn
    checkpoint), `max_to_keep` retention, optional monitor-metric
    "save best only", and async saves whose errors surface at train
    end instead of being lost."""

    def __init__(self, save_freq=1, save_dir=None, *, max_to_keep=None,
                 monitor="loss", mode="min", save_best_only=False,
                 async_save=False):
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.max_to_keep = max_to_keep
        self.monitor = monitor
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.save_best_only = save_best_only
        self.async_save = async_save
        self.best = None
        self._mgr = None

    def _manager(self):
        if self._mgr is None:
            from ..io.checkpoint import CheckpointManager

            self._mgr = CheckpointManager(
                self.save_dir, max_to_keep=self.max_to_keep,
                async_save=self.async_save)
        return self._mgr

    def _is_better(self, v):
        if self.best is None:
            return True
        return v < self.best if self.mode == "min" else v > self.best

    def on_epoch_end(self, epoch, logs=None):
        if not self.save_dir or epoch % self.save_freq != 0:
            return
        if self.save_best_only:
            v = (logs or {}).get(self.monitor)
            if v is not None:
                v = float(np.ravel(v)[0])
                if not self._is_better(v):
                    return
                self.best = v
        state = {"epoch": int(epoch),
                 "model": self.model.network.state_dict()}
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None:
            state["opt"] = opt.state_dict()
        self._manager().save(epoch, state, force=True)

    def on_train_end(self, logs=None):
        if self._mgr is not None:
            self._mgr.wait()   # surface async-save errors, don't lose


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return
        v = float(np.ravel(v)[0])
        better = (self.best is None or
                  (v < self.best - self.min_delta if self.mode == "min"
                   else v > self.best + self.min_delta))
        if better:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            self._step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            self._step()

    def _step(self):
        opt = self.model._optimizer
        lr = getattr(opt, "_lr", None) or getattr(opt, "_learning_rate",
                                                  None)
        if hasattr(lr, "step"):
            lr.step()


class CallbackList:
    def __init__(self, callbacks, model):
        self.callbacks = callbacks
        for c in callbacks:
            c.set_model(model)

    def on_train_begin(self):
        for c in self.callbacks:
            c.on_train_begin()

    def on_train_end(self):
        for c in self.callbacks:
            c.on_train_end()

    def on_epoch_begin(self, epoch):
        for c in self.callbacks:
            c.on_epoch_begin(epoch)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_train_batch_end(step, logs)


def config_callbacks(callbacks, model, epochs, verbose, log_freq):
    cbs = list(callbacks or [])
    if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
        cbs.insert(0, ProgBarLogger(log_freq, verbose))
    return CallbackList(cbs, model)
