"""High-level Model API.

Reference parity: python/paddle/hapi/model.py:788 (Model, fit :1243,
evaluate, predict, save/load; Static/DynamicGraphAdapter). TPU-native
design: one adapter — the eager engine with jit-compiled train steps; data
parallelism comes from fleet/SPMD rather than a separate static adapter.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..io import DataLoader, Dataset
from ..io.serialization import load as _load
from ..io.serialization import save as _save
from . import callbacks as cbks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._loss = None
        self._optimizer = None
        self._metrics = []
        self.stop_training = False
        # per-fit step-timing telemetry (see fit_report()); refreshed
        # by every fit() call
        self.fit_stats = None

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = _as_list(inputs)
        labels = _as_list(labels)
        outputs = self.network(*[to_tensor(x) for x in inputs])
        losses = self._loss(*_as_list(outputs),
                            *[to_tensor(y) for y in labels])
        loss = losses if isinstance(losses, Tensor) else sum(losses)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([float(loss.numpy())], metrics) if metrics else \
            [float(loss.numpy())]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad

        self.network.eval()
        inputs = _as_list(inputs)
        labels = _as_list(labels)
        with no_grad():
            outputs = self.network(*[to_tensor(x) for x in inputs])
            losses = self._loss(*_as_list(outputs),
                                *[to_tensor(y) for y in labels]) \
                if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        loss_val = [float(losses.numpy())] if losses is not None else []
        return (loss_val, metrics) if metrics else loss_val

    def generate(self, *args, **kwargs):
        """Autoregressive generation through the network's static
        KV-cache decode engine (nn.TransformerDecoder.generate /
        text.generation.DecodeEngine): prefill once, then the whole
        decode as one jitted scan."""
        net = self.network
        if not hasattr(net, "generate"):
            raise AttributeError(
                f"{type(net).__name__} has no generate(); attach a "
                "text.generation.DecodeEngine or use a decoder stack "
                "with TransformerDecoder.generate")
        net.eval()
        return net.generate(*args, **kwargs)

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad

        self.network.eval()
        with no_grad():
            out = self.network(*[to_tensor(x) for x in _as_list(inputs)])
        return [o.numpy() for o in _as_list(out)]

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self._metrics:
            state = m.compute(*_as_list(outputs),
                              *[to_tensor(y) for y in labels])
            vals.append(m.update(*_as_list(state)))
        return vals

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            resume=None):
        """Train the prepared model. `resume` names a crash-safe checkpoint directory
        (io.checkpoint.CheckpointManager): every finished epoch is
        checkpointed atomically (model + optimizer + numpy RNG state),
        and a rerun with the same `resume` dir restores the newest
        VALID checkpoint — torn/corrupt steps from a mid-save kill are
        skipped — and continues from the next epoch, bit-matching the
        uninterrupted run."""
        train_loader = _as_loader(train_data, batch_size, shuffle, drop_last,
                                  num_workers)
        eval_loader = _as_loader(eval_data, batch_size, False, False,
                                 num_workers) if eval_data is not None \
            else None
        ckpt_mgr = None
        start_epoch = 0
        if resume:
            from ..io.checkpoint import CheckpointManager

            ckpt_mgr = CheckpointManager(resume, max_to_keep=3)
            if ckpt_mgr.latest_step() is not None:
                snap = ckpt_mgr.restore()
                self._load_train_state(snap)
                start_epoch = int(snap["epoch"]) + 1
        cbk_list = cbks.config_callbacks(callbacks, self, epochs, verbose,
                                         log_freq)
        cbk_list.on_train_begin()
        history = []
        # step-timing telemetry: two clock reads per step feed the
        # training-goodput gauge (useful step wall / total fit wall —
        # the loader/eval/checkpoint overhead is the difference) and
        # the per-step latency profiler.costs' training-MFU math uses
        import time as _time

        t_fit0 = _time.perf_counter()
        n_steps = 0
        train_s = 0.0
        step_times = []            # bounded: last 2048 step walls
        for epoch in range(start_epoch, epochs):
            cbk_list.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                ins, lbs = _split_batch(batch, self._n_inputs())
                t0 = _time.perf_counter()
                res = self.train_batch(ins, lbs)
                dt = _time.perf_counter() - t0
                n_steps += 1
                train_s += dt
                if len(step_times) < 2048:
                    step_times.append(dt)
                else:
                    step_times[n_steps % 2048] = dt
                logs = _logs_from(res, self._metrics)
                cbk_list.on_batch_end("train", step, logs)
                if num_iters is not None and step + 1 >= num_iters:
                    break
            history.append(logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, verbose=0)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if ckpt_mgr is not None:
                # last op of the epoch, so the snapshot (incl. RNG
                # state) is exactly what the next epoch starts from
                ckpt_mgr.save(epoch, self._train_state(epoch),
                              force=True)
            cbk_list.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cbk_list.on_train_end()
        wall_s = _time.perf_counter() - t_fit0
        self.fit_stats = {
            "steps": n_steps,
            "train_s": round(train_s, 6),
            "wall_s": round(wall_s, 6),
            "step_ms_p50": round(
                float(np.median(step_times)) * 1e3, 3)
            if step_times else 0.0,
            # training goodput: the fraction of fit wall spent in the
            # optimizer step proper (loader, eval, checkpointing and
            # callback overheads are the 1 - goodput remainder)
            "goodput": round(train_s / wall_s, 4) if wall_s > 0
            else 0.0,
        }
        return history

    def fit_report(self, flops_per_step=None, spec=None):
        """The last fit()'s step-timing telemetry, optionally extended
        with training MFU when the caller knows the per-step flops
        (e.g. from a `profiler.costs` book entry): mean achieved flop
        rate over the steps vs the DeviceSpec peak."""
        if self.fit_stats is None:
            raise RuntimeError("fit() has not run yet")
        out = dict(self.fit_stats)
        if flops_per_step is not None and out["steps"]:
            from ..profiler import costs as _costs

            spec = spec if spec is not None else _costs.detect_spec()
            mean_dt = out["train_s"] / out["steps"]
            out["device"] = spec.as_dict()
            out["mfu"] = round(
                _costs.mfu(float(flops_per_step), mean_dt, spec), 6)
        return out

    def _train_state(self, epoch):
        """Everything fit(resume=...) needs to continue bit-exactly."""
        state = {"epoch": int(epoch),
                 "model": self.network.state_dict(),
                 "numpy_rng": np.random.get_state()}
        if self._optimizer is not None:
            state["opt"] = self._optimizer.state_dict()
        return state

    def _load_train_state(self, state):
        self.network.set_state_dict(state["model"])
        if self._optimizer is not None and "opt" in state:
            self._optimizer.set_state_dict(state["opt"])
        if "numpy_rng" in state:
            np.random.set_state(state["numpy_rng"])

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = _as_loader(eval_data, batch_size, False, False, num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            ins, lbs = _split_batch(batch, self._n_inputs())
            res = self.eval_batch(ins, lbs)
            if isinstance(res, tuple):
                losses.extend(res[0])
            else:
                losses.extend(res)
            if num_iters is not None and step + 1 >= num_iters:
                break
        out = {"loss": [float(np.mean(losses))] if losses else []}
        for m in self._metrics:
            out[_name_of(m)] = m.accumulate()
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = _as_loader(test_data, batch_size, False, False, num_workers)
        outs = []
        for batch in loader:
            ins = batch[0] if isinstance(batch, tuple) else batch
            outs.append(self.predict_batch(ins))
        if stack_outputs:
            n = len(outs[0])
            return [np.concatenate([o[i] for o in outs]) for i in range(n)]
        return outs

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams")
        self.network.set_state_dict(state)
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)

    def _n_inputs(self):
        if self._inputs is None:
            return 1
        return len(_as_list(self._inputs))


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _as_loader(data, batch_size, shuffle, drop_last, num_workers):
    if data is None or isinstance(data, DataLoader):
        return data
    if isinstance(data, Dataset):
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)
    return data


def _split_batch(batch, n_inputs):
    if isinstance(batch, (list, tuple)):
        return list(batch[:n_inputs]), list(batch[n_inputs:])
    return [batch], []


def _logs_from(res, metrics):
    logs = {}
    if isinstance(res, tuple):
        losses, mvals = res
        logs["loss"] = losses
        for m, v in zip(metrics, mvals):
            logs[_name_of(m)] = v
    else:
        logs["loss"] = res
    return logs


def _name_of(m):
    n = m.name()
    return n if isinstance(n, str) else n[0]


def summary(net, input_size=None, dtypes=None, input=None):
    """paddle.summary parity (hapi/model_summary.py): parameter table +
    totals; with input_size (or a concrete input), runs a forward pass in
    eval mode and reports the output shape too."""
    lines = [f"{type(net).__name__}:"]
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape))
        total += n
        if getattr(p, "trainable", True):
            trainable += n
        lines.append(f"  {name:<40} {str(p.shape):<20} {n}")
    lines.append(f"Total params: {total}")
    lines.append(f"Trainable params: {trainable}")
    lines.append(f"Non-trainable params: {total - trainable}")
    out_shape = None
    try:
        if input is None and input_size is not None:
            from ..core.tensor import to_tensor

            shape = list(input_size)
            input = to_tensor(np.zeros(
                shape, dtypes if isinstance(dtypes, str) else "float32"))
        if input is not None:
            was_training = getattr(net, "training", False)
            net.eval()
            try:
                out = net(input)
            finally:
                if was_training:
                    net.train()
            first = out[0] if isinstance(out, (list, tuple)) else out
            out_shape = list(first.shape)
            lines.append(f"Output shape: {out_shape}")
    except Exception as e:  # shape probe is best-effort
        lines.append(f"(forward probe skipped: {e})")
    print("\n".join(lines))
    res = {"total_params": total, "trainable_params": trainable}
    if out_shape is not None:
        res["output_shape"] = out_shape
    return res
