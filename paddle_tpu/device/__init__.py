"""paddle.device namespace."""
from ..core.place import (CPUPlace, TPUPlace, accelerator_count,  # noqa
                          get_device, set_device)


def get_available_device():
    return [get_device()]


def device_count():
    return accelerator_count()


class cuda:  # namespace shim: paddle.device.cuda.*
    @staticmethod
    def device_count():
        return accelerator_count()

    @staticmethod
    def synchronize(device=None):
        import jax

        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    cuda.synchronize(device)
