"""Sparse support: SelectedRows gradients + COO/CSR tensors.

Reference parity: framework/selected_rows.h:32 — SelectedRows {rows, value}
used for embedding gradients (lookup_table_op.cc grad with is_sparse=True,
operators/math/selected_rows_functor.h MergeAdd). TPU-native design
(SURVEY.md §7 hard part 3): XLA has no sparse tensors; SelectedRows is an
(indices, values) pair whose reduction lowers to segment-sum/scatter-add,
so a 30M-row vocab never materializes a dense gradient. The eager tape
emits SelectedRows from `F.embedding(..., sparse=True)`; optimizers apply
row-wise updates; the PS client pushes (rows, values) directly
(large_scale_kv.h:762 capability).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


def _raw(v):
    return v._data if isinstance(v, Tensor) else v


class SelectedRows:
    """{rows: int32[n], values: [n, ...], height: V} — row-sparse tensor.

    Rows may repeat; `merge()` sums duplicates (MergeAdd parity). Supports
    `+` with another SelectedRows (concat — GradientAccumulator semantics
    for sparse grads) or with a dense array (densifies).
    """

    def __init__(self, rows, values, height):
        import jax.numpy as jnp

        self.rows = jnp.asarray(_raw(rows)).astype(jnp.int32).reshape(-1)
        self.values = _raw(values)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype),
                            self.height)

    def to_dense(self):
        import jax.numpy as jnp

        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        # mode='drop': out-of-range rows (e.g. unique() fill values) vanish
        return Tensor._wrap(dense.at[self.rows].add(self.values,
                                                    mode="drop"))

    def merge(self, shape_stable=False):
        """Merge duplicate rows (selected_rows_functor MergeAdd parity).

        shape_stable=True keeps the fixed-size unique output (padded with
        out-of-range fill rows = height, zero values) — jit-friendly: no
        host sync, no recompile per distinct nnz; consumers must use
        mode='drop' scatters, which all sparse optimizer rules do.
        shape_stable=False filters the fill rows on the host (exact nnz,
        for host-side consumers like the PS push)."""
        import jax

        import jax.numpy as jnp

        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        merged = jax.ops.segment_sum(self.values, inv, uniq.shape[0])
        if shape_stable:
            return SelectedRows(uniq, merged, self.height)
        keep = np.asarray(uniq) < self.height
        return SelectedRows(np.asarray(uniq)[keep],
                            merged[np.asarray(keep)], self.height)

    def __add__(self, other):
        import jax.numpy as jnp

        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]), self.height)
        # dense + sparse -> dense
        dense = _raw(other)
        return dense.at[self.rows].add(self.values.astype(dense.dtype),
                                       mode="drop")

    __radd__ = __add__

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"value_shape={tuple(self.values.shape)})")


class SparseCooTensor:
    """paddle.sparse COO tensor (paddle 2.x incubate.sparse parity):
    indices [ndim, nnz] int64, values [nnz, ...dense_dims], shape."""

    def __init__(self, indices, values, shape):
        import jax.numpy as jnp

        self.indices = jnp.asarray(_raw(indices)).astype(jnp.int64)
        self.values = jnp.asarray(_raw(values))
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self.values.dtype

    def nnz(self):
        return int(self.values.shape[0])

    def to_dense(self):
        import jax.numpy as jnp

        sd = self.indices.shape[0]
        dense = jnp.zeros(self._shape[:sd] + tuple(self.values.shape[1:]),
                          self.values.dtype)
        idx = tuple(self.indices[d] for d in range(sd))
        return Tensor._wrap(dense.at[idx].add(self.values))

    def coalesce(self):
        """Sum duplicate coordinates."""
        import jax

        import jax.numpy as jnp

        sd = self.indices.shape[0]
        strides = [int(np.prod(self._shape[d + 1:sd], dtype=np.int64))
                   for d in range(sd)]
        flat = sum(self.indices[d] * int(strides[d]) for d in range(sd))
        uniq, inv = jnp.unique(flat, return_inverse=True,
                               size=flat.shape[0], fill_value=-1)
        vals = jax.ops.segment_sum(self.values, inv, uniq.shape[0])
        keep = np.asarray(uniq) >= 0
        uniq_k = np.asarray(uniq)[keep]
        coords = []
        rem = uniq_k
        for d in range(sd):
            coords.append(rem // int(strides[d]))
            rem = rem % int(strides[d])
        return SparseCooTensor(np.stack(coords), vals[np.asarray(keep)],
                               self._shape)

    def __repr__(self):
        return f"SparseCooTensor(shape={self._shape}, nnz={self.nnz()})"


def sparse_coo_tensor(indices, values, shape, dtype=None):
    """paddle.sparse.sparse_coo_tensor parity."""
    v = np.asarray(_raw(values))
    if dtype is not None:
        from ..core.dtypes import convert_dtype

        v = v.astype(convert_dtype(dtype))
    return SparseCooTensor(np.asarray(_raw(indices)), v, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """CSR expressed over the COO core (2-D only)."""
    crows = np.asarray(_raw(crows)).astype(np.int64)
    cols = np.asarray(_raw(cols)).astype(np.int64)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    return sparse_coo_tensor(np.stack([rows, cols]), values, shape, dtype)


def matmul(sp, dense):
    """COO (2-D) @ dense via segment-sum — the XLA-native SpMM."""
    import jax

    d = _raw(dense)
    if isinstance(sp, SparseCooTensor):
        rows, cols = sp.indices[0], sp.indices[1]
        contrib = sp.values[:, None] * d[cols]
        out = jax.ops.segment_sum(contrib, rows.astype(np.int32),
                                  sp._shape[0])
        return Tensor._wrap(out)
    raise TypeError(f"matmul expects SparseCooTensor, got {type(sp)}")
