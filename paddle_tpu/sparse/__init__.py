"""Sparse support.

Reference parity: framework/selected_rows.h:32 — SelectedRows {rows, value}
used for embedding gradients. TPU-native design (SURVEY.md §7 hard part 3):
XLA has no sparse tensors; SelectedRows is a host-side (indices, values)
pair whose reduction lowers to segment-sum. Provided for API parity and for
the parameter-server sparse path.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class SelectedRows:
    def __init__(self, rows, values, height):
        import jax.numpy as jnp

        self.rows = jnp.asarray(rows, dtype=jnp.int32)
        self.values = values._data if isinstance(values, Tensor) else values
        self.height = int(height)

    def to_dense(self):
        import jax

        import jax.numpy as jnp

        dense = jnp.zeros((self.height,) + tuple(self.values.shape[1:]),
                          self.values.dtype)
        return Tensor._wrap(dense.at[self.rows].add(self.values))

    def merge(self):
        """Merge duplicate rows (selected_rows_functor MergeAdd parity)."""
        import jax

        import jax.numpy as jnp

        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=self.height)
        merged = jax.ops.segment_sum(self.values, inv, uniq.shape[0])
        keep = uniq < self.height
        return SelectedRows(np.asarray(uniq)[np.asarray(keep)],
                            merged[np.asarray(keep)], self.height)


def sparse_coo_tensor(indices, values, shape, dtype=None):
    raise NotImplementedError("COO tensors land with the sparse op set")
