"""Deterministic fault injection for the serving / IO / training paths.

The production code is instrumented with *named fault points* — module
level markers created once at import (serving.slot_join / prefill /
decode_step / prefill_splice, scheduler.admit, checkpoint.write/read,
dataloader.next, and tuning.cache_load — the persistent AOT compile
cache's entry reads, so chaos runs can hand the startup path torn
blobs):

    _PT_DECODE = faults.point("serving.decode_step")
    ...
    def _decode(...):
        _PT_DECODE()                    # hit: no-op unless armed

IO points pass their payload through the hit so an armed *corrupt* plan
can mutate the bytes in flight:

    buf = _PT_WRITE(payload=buf)

Tests (and `tools/chaos_check.py`) arm points with injection *plans*:

    with faults.inject("serving.decode_step", on="nth", n=3):
        ...                             # 3rd hit raises InjectedFault

    faults.inject("checkpoint.write", on="every", k=2,
                  action="corrupt")     # flip a byte every 2nd write
    faults.inject("serving.prefill", on="prob", p=0.2, seed=7,
                  action="delay", delay_s=0.05)

Plan semantics (each injection keeps its OWN hit counter, so every
failure mode reproduces exactly across runs):

  * ``on="nth"``   — fire on exactly the Nth hit after install;
  * ``on="every"`` — fire on every Kth hit (K, 2K, 3K, ...);
  * ``on="prob"``  — fire with probability p from a private
    ``random.Random(seed)`` stream (seeded-deterministic);
  * ``on="always"``— fire on every hit;
  * ``max_fires``  — cap on total fires for any plan.

Actions: ``raise`` (the given exception class or instance — default
`InjectedFault`), ``delay`` (sleep `delay_s`, e.g. to trip watchdogs),
``corrupt`` (transform the payload; default flips one byte of a bytes
payload). Multiple injections on one point compose in install order;
delay/corrupt actions accumulate, a raise aborts the hit.

Disarmed cost is ONE module-global boolean read per hit — no locks, no
dict lookups, no per-hit allocation — so leaving the instrumentation in
production code is free (`test_faults.py` pins this).
"""
from __future__ import annotations

import random
import threading
import time

__all__ = [
    "InjectedFault", "FaultPoint", "Injection", "point", "points",
    "inject", "reset", "armed", "hit_counts",
]

_ARMED = False                 # the only thing a disarmed hit reads
_LOCK = threading.RLock()
_POINTS = {}                   # name -> FaultPoint (import-time registry)
_INJECTIONS = {}               # name -> [Injection, ...] (install order)
_HIT_COUNTS = {}               # name -> hits observed while armed


class InjectedFault(RuntimeError):
    """Default exception raised by an armed ``action="raise"`` plan."""


class FaultPoint:
    """A named instrumentation marker. Calling it is the *hit*: returns
    the payload (possibly corrupted), raises or delays per the armed
    plans, and is a single-boolean no-op when nothing is armed."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __call__(self, payload=None):
        if not _ARMED:
            return payload
        return _fire(self.name, payload)

    def __repr__(self):
        return f"FaultPoint({self.name!r})"


def point(name):
    """Register (idempotently) and return the named fault point."""
    with _LOCK:
        p = _POINTS.get(name)
        if p is None:
            p = _POINTS[name] = FaultPoint(str(name))
        return p


def points():
    """Sorted names of every registered fault point."""
    with _LOCK:
        return sorted(_POINTS)


def _default_corrupt(payload):
    """Flip one byte in the middle of a bytes payload (a detectable,
    deterministic 'torn write'); non-bytes payloads pass unchanged."""
    if isinstance(payload, (bytes, bytearray)) and len(payload):
        b = bytearray(payload)
        b[len(b) // 2] ^= 0xFF
        return bytes(b)
    return payload


class Injection:
    """One armed plan on one point. Context manager: ``with
    faults.inject(...):`` removes it on exit. `hits`/`fired` counters
    make 'counters match injected faults' assertions exact."""

    _ONS = ("always", "nth", "every", "prob")
    _ACTIONS = ("raise", "delay", "corrupt")

    def __init__(self, name, *, on, n, k, p, seed, action, exc,
                 delay_s, corrupt, max_fires):
        if on not in self._ONS:
            raise ValueError(f"on must be one of {self._ONS}, got {on!r}")
        if action not in self._ACTIONS:
            raise ValueError(
                f"action must be one of {self._ACTIONS}, got {action!r}")
        if on == "every" and k < 1:
            raise ValueError("every-K plans need k >= 1")
        self.point = name
        self.on = on
        self.n = int(n)
        self.k = int(k)
        self.p = float(p)
        self.action = action
        self.exc = exc
        self.delay_s = float(delay_s)
        self.corrupt = corrupt
        self.max_fires = None if max_fires is None else int(max_fires)
        self.hits = 0
        self.fired = 0
        self._rng = random.Random(seed)

    # called under _LOCK
    def _should_fire(self):
        self.hits += 1
        if self.max_fires is not None and self.fired >= self.max_fires:
            return False
        if self.on == "always":
            return True
        if self.on == "nth":
            return self.hits == self.n
        if self.on == "every":
            return self.hits % self.k == 0
        return self._rng.random() < self.p

    def remove(self):
        global _ARMED
        with _LOCK:
            lst = _INJECTIONS.get(self.point)
            if lst is not None and self in lst:
                lst.remove(self)
                if not lst:
                    del _INJECTIONS[self.point]
            if not _INJECTIONS:
                _ARMED = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove()
        return False

    def __repr__(self):
        return (f"Injection({self.point!r}, on={self.on!r}, "
                f"action={self.action!r}, hits={self.hits}, "
                f"fired={self.fired})")


def inject(name, *, on="always", n=1, k=1, p=1.0, seed=0,
           action="raise", exc=InjectedFault, delay_s=0.01,
           corrupt=None, max_fires=None):
    """Arm an injection plan on the named point; returns the
    `Injection` (usable as a context manager). Arms the global harness;
    `reset()` or removing the last injection disarms it."""
    global _ARMED
    inj = Injection(name, on=on, n=n, k=k, p=p, seed=seed, action=action,
                    exc=exc, delay_s=delay_s, corrupt=corrupt,
                    max_fires=max_fires)
    with _LOCK:
        point(name)
        _INJECTIONS.setdefault(name, []).append(inj)
        _ARMED = True
    return inj


def _fire(name, payload):
    # decide under the lock (counters stay exact under threads), act
    # outside it (a delay must not serialize unrelated points)
    with _LOCK:
        _HIT_COUNTS[name] = _HIT_COUNTS.get(name, 0) + 1
        firing = []
        for inj in _INJECTIONS.get(name, ()):
            if inj._should_fire():
                inj.fired += 1
                firing.append(inj)
    for inj in firing:
        if inj.action == "delay":
            time.sleep(inj.delay_s)
        elif inj.action == "corrupt":
            fn = inj.corrupt if inj.corrupt is not None else \
                _default_corrupt
            payload = fn(payload)
        else:
            e = inj.exc
            if isinstance(e, BaseException):
                raise e
            raise e(f"injected fault at {name!r} (hit #{inj.hits})")
    return payload


def reset():
    """Remove every injection, zero the hit counters, disarm. Test
    teardowns call this so faults never leak across tests."""
    global _ARMED
    with _LOCK:
        _INJECTIONS.clear()
        _HIT_COUNTS.clear()
        _ARMED = False


def armed():
    return _ARMED


def hit_counts():
    """Per-point hit counts observed while armed (disarmed hits are
    never counted — they must cost nothing)."""
    with _LOCK:
        return dict(_HIT_COUNTS)
