"""Test-support utilities that ship with the package (importable from
production code): deterministic fault injection lives in
`paddle_tpu.testing.faults`. Nothing here pulls in jax — the serving
runtime, checkpoint IO, and dataloader import it at module load."""
from . import faults  # noqa: F401

__all__ = ["faults"]
