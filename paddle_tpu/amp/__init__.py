"""Automatic mixed precision.

Reference parity: imperative/amp_auto_cast.h:29 + fluid/dygraph/amp/
(auto_cast.py:90 amp_guard, loss_scaler.py:27 AmpScaler) and the static
rewriter contrib/mixed_precision/decorator.py:218. TPU-native design:
bfloat16 is the native mixed-precision type — no loss scaling is *needed*
(bf16 has fp32's exponent range), but GradScaler keeps API parity and also
supports float16 semantics for completeness.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np

from ..core.dtypes import bfloat16, float16, float32
from ..core.tensor import Tensor

_state = threading.local()

# ops that run in low precision under autocast level O1 (matmul/conv feed the
# MXU; mirrors fp16_lists.py:20 white_list)
WHITE_LIST = {"matmul", "linear", "conv2d", "conv1d", "bmm", "mul", "einsum",
              "sdpa"}
# ops kept in fp32 (reductions, losses, norms — mirrors black_list)
BLACK_LIST = {"softmax_with_cross_entropy", "cross_entropy", "reduce_mean",
              "reduce_sum", "layer_norm", "batch_norm", "log_softmax",
              "norm", "logsumexp", "bce_logits", "bce_loss"}


def _amp_dtype():
    return getattr(_state, "dtype", None)


def _amp_level():
    return getattr(_state, "level", "O0")


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast / fluid amp_guard parity."""
    prev = (_amp_dtype(), _amp_level(),
            getattr(_state, "white", None), getattr(_state, "black", None))
    if enable:
        _state.dtype = bfloat16 if str(dtype) in ("bfloat16", "bf16") else \
            float16
        _state.level = level
        _state.white = WHITE_LIST | set(custom_white_list or ())
        _state.black = (BLACK_LIST - set(custom_white_list or ())) | set(
            custom_black_list or ())
    else:
        _state.dtype = None
        _state.level = "O0"
    try:
        yield
    finally:
        (_state.dtype, _state.level, _state.white, _state.black) = prev


amp_guard = auto_cast


def cast_inputs_if_amp(op_name, raws):
    """Hook used by the eager dispatcher: cast inputs per autocast policy."""
    dt = _amp_dtype()
    if dt is None:
        return raws
    white = getattr(_state, "white", WHITE_LIST)
    black = getattr(_state, "black", BLACK_LIST)
    level = _amp_level()
    import jax.numpy as jnp

    def is_float(a):
        return a.dtype in (jnp.float32, jnp.float16, jnp.bfloat16)

    if op_name in black:
        return [a.astype(jnp.float32) if is_float(a) else a for a in raws]
    if level == "O2" or op_name in white:
        return [a.astype(dt) if is_float(a) else a for a in raws]
    return raws


class GradScaler:
    """paddle.amp.GradScaler / fluid AmpScaler (loss_scaler.py:27) parity.

    With bfloat16 the scale stays fixed at init (no overflow risk); with
    float16 the full dynamic-scaling state machine runs.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False

    def scale(self, loss):
        if not self._enable:
            return loss
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        import jax.numpy as jnp

        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameters:
            if p.grad is not None:
                g = p.grad._data * inv
                if not bool(jnp.isfinite(g).all()):
                    found = True
                p.grad._data = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        # undo the scaling on grads, check finiteness, then step
        self.step(optimizer)

    def update(self):
        pass  # state already updated in step()

    def _update(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0

    def is_enable(self):
        return self._enable

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "good": self._good, "bad": self._bad}

    def set_state_dict(self, s):
        self._scale = s.get("scale", self._scale)
        self._good = s.get("good", 0)
        self._bad = s.get("bad", 0)


AmpScaler = GradScaler


def decorate(models=None, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts the model to the amp dtype."""
    dt = bfloat16 if str(dtype) in ("bfloat16", "bf16") else float16
    if level == "O2" and models is not None:
        ms = models if isinstance(models, (list, tuple)) else [models]
        for m in ms:
            m.to(dtype=dt)
    if optimizers is None:
        return models
    return models, optimizers
