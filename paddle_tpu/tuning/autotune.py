"""Block-shape sweep driver for the pallas kernels.

TVM's conclusion (PAPERS.md) — searched tile selection beats
hand-picked tiles by integer factors — applied to this repo's four
kernel families. For each (kernel, head_dim, seq bucket, dtype) key
the sweep:

  1. enumerates the legal candidate configs (`candidates()`: block
     pairs that tile the sequence, split factors that keep lane-
     friendly 128-multiples — the same legality gates the kernels
     enforce);
  2. prunes candidates whose ANALYTIC roofline lower bound
     (`analytic_cost()` flops/bytes against the `DeviceSpec` peaks —
     causal block-granularity overshoot included) already exceeds the
     incumbent's measured time: a candidate that cannot win is never
     timed;
  3. times the survivors with the shared `tools/op_bench.measure`
     harness (median-of-k pair slopes, the 1-core-box discipline);
  4. stops early once the incumbent sits within `stop_factor` of the
     key's roofline — the DeviceSpec peak is the sweep's floor;
  5. records the winner (config + step_us + source="sweep") for
     `TuningTable.put`, keyed by device_kind.

`fallback_config()` reproduces the hand-picked constants the kernels
used before tuning existed; the committed default table is GENERATED
from it (`fallback_entries()`), which is what makes the tuned-off and
untuned-device paths bit-identical to the old kernels — pinned by
tests/test_tuning.py.
"""
from __future__ import annotations

import math

from . import table as _table

__all__ = ["candidates", "fallback_config", "fallback_entries",
           "analytic_cost", "roofline_seconds", "prune", "sweep_key",
           "build_runner", "default_measurer", "apply_report",
           "DEFAULT_KEYS"]

#: block-size ladder the fwd/bwd sweep draws from (the v5e sweep of
#: tools/tune_flash.py measured over exactly this set)
BLOCK_LADDER = (128, 256, 384, 512)
#: split-K ladder for the decode/verify kernels
SPLIT_LADDER = (1, 2, 4, 8, 16)


def _op_bench():
    """tools/op_bench.py as a module (tools/ is not a package; the
    repo's tests/tools import it by path the same way)."""
    import os
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import op_bench

    return op_bench


# ----------------------------------------------------------------------
# keys, candidates, fallbacks
# ----------------------------------------------------------------------

def _dims_of(kernel, key):
    """Parse a key tuple back into named dims. Key layouts (every seq
    component pre-bucketed by the caller):

        flash_fwd/bwd       (d, sq, sk, dtype)
        flash_decode        (d, L, dtype)
        flash_verify        (d, L, dtype, T)
        paged_flash_decode  (d, psz, dtype)
        paged_flash_verify  (d, psz, dtype, T)
        int8_matmul         (d, n, dtype)   d = contraction bucket,
                                            n = output-channel bucket
        lora_matmul         (d, r, dtype)   d = model-dim bucket,
                                            r = adapter rank
    """
    if kernel in ("flash_fwd", "flash_bwd"):
        d, sq, sk, dt = key
        return {"d": int(d), "sq": int(sq), "sk": int(sk),
                "dtype": str(dt)}
    if kernel == "flash_decode":
        d, L, dt = key
        return {"d": int(d), "L": int(L), "dtype": str(dt)}
    if kernel == "flash_verify":
        d, L, dt, T = key
        return {"d": int(d), "L": int(L), "dtype": str(dt),
                "T": int(T)}
    if kernel == "paged_flash_decode":
        d, psz, dt = key
        return {"d": int(d), "psz": int(psz), "dtype": str(dt)}
    if kernel == "paged_flash_verify":
        d, psz, dt, T = key
        return {"d": int(d), "psz": int(psz), "dtype": str(dt),
                "T": int(T)}
    if kernel == "int8_matmul":
        d, n, dt = key
        return {"d": int(d), "n": int(n), "dtype": str(dt)}
    if kernel == "lora_matmul":
        d, r, dt = key
        return {"d": int(d), "r": int(r), "dtype": str(dt)}
    raise ValueError(f"unknown kernel {kernel!r}")


def candidates(kernel, key):
    """Legal configs for (kernel, key) — the kernels' own tiling gates
    applied up front so the sweep never times an unbuildable config."""
    dims = _dims_of(kernel, key)
    if kernel in ("flash_fwd", "flash_bwd"):
        sq, sk = dims["sq"], dims["sk"]
        out = []
        for bq in BLOCK_LADDER:
            for bk in BLOCK_LADDER:
                if sq % min(bq, sq) == 0 and sk % min(bk, sk) == 0:
                    out.append({"block_q": bq, "block_k": bk})
        return out
    if kernel in ("flash_decode", "flash_verify"):
        L = dims["L"]
        return [{"split_k": n} for n in SPLIT_LADDER
                if L % n == 0 and (L // n) % 128 == 0]
    if kernel == "paged_flash_decode":
        # dispatch-level knob only: the grid is (slot*head, page)
        return [{"kernel": True}, {"kernel": False}]
    if kernel == "paged_flash_verify":
        # the kernel grid is fixed by the pages, so kernel-on has no
        # block freedom; kernel-off falls back to gather + the dense
        # verify dispatch, whose split_k ladder IS tunable (legality
        # at the nominal 8-mapped-pages logical length)
        L = dims["psz"] * 8
        return [{"kernel": True, "split_k": 0}] + \
            [{"kernel": False, "split_k": n} for n in SPLIT_LADDER
             if L % n == 0 and (L // n) % 128 == 0]
    if kernel == "int8_matmul":
        # tile ladder at the nominal decode-batch m (ops.quant's
        # INT8_BLOCK_* sets); legality = the tile divides the bucket
        from ..ops.quant import INT8_BLOCK_M, INT8_BLOCK_N

        n = dims["n"]
        return [{"block_m": bm, "block_n": bn}
                for bm in INT8_BLOCK_M for bn in INT8_BLOCK_N
                if n % bn == 0]
    if kernel == "lora_matmul":
        # dispatch-level knob only: the gathered grid is (slot,)
        return [{"kernel": True}, {"kernel": False}]
    raise ValueError(f"unknown kernel {kernel!r}")


def fallback_config(kernel, key):
    """The hand-picked constants the kernels shipped with — what an
    untuned device (or PT_TUNING=0) uses, verbatim. Mirrors
    `ops/attention.py`'s heuristics via their own functions, so the
    two can never drift."""
    from ..ops import attention as A

    dims = _dims_of(kernel, key)
    if kernel in ("flash_fwd", "flash_bwd"):
        bq, bk = A._pick_blocks_heuristic(dims["sq"], dims["sk"])
        return {"block_q": bq, "block_k": bk}
    if kernel in ("flash_decode", "flash_verify"):
        return {"split_k": A._pick_decode_splits_heuristic(dims["L"])}
    if kernel == "paged_flash_decode":
        return {"kernel": True}
    if kernel == "paged_flash_verify":
        return dict(A._paged_verify_heuristic())
    if kernel == "int8_matmul":
        from ..ops import quant as Q

        bm, bn = Q._pick_int8_blocks_heuristic(8, dims["n"])
        return {"block_m": bm, "block_n": bn}
    if kernel == "lora_matmul":
        from ..ops import quant as Q

        return dict(Q._lora_dispatch_heuristic())
    raise ValueError(f"unknown kernel {kernel!r}")


#: the key grid the committed fallback table covers: every decode-pool
#: shape the engines bucket to, plus the training seq lengths the
#: benches exercise
DEFAULT_KEYS = {
    "flash_fwd": [(d, s, s, dt)
                  for d in (64, 128) for s in (512, 1024, 2048, 4096)
                  for dt in ("float32", "bfloat16")],
    "flash_bwd": [(d, s, s, dt)
                  for d in (64, 128) for s in (512, 1024, 2048, 4096)
                  for dt in ("float32", "bfloat16")],
    "flash_decode": [(d, L, dt)
                     for d in (64, 128) for L in (512, 2048, 8192)
                     for dt in ("float32", "bfloat16")],
    "flash_verify": [(d, L, dt, T)
                     for d in (64, 128) for L in (512, 2048)
                     for dt in ("float32", "bfloat16")
                     for T in (2, 4, 8)],
    "paged_flash_decode": [(d, psz, dt)
                           for d in (64, 128) for psz in (16, 64)
                           for dt in ("float32", "int8")],
    "paged_flash_verify": [(d, psz, dt, T)
                           for d in (64, 128) for psz in (16, 64)
                           for dt in ("float32", "int8")
                           for T in (2, 4)],
    "int8_matmul": [(d, n, dt)
                    for d in (256, 1024) for n in (256, 1024)
                    for dt in ("float32", "bfloat16")],
    "lora_matmul": [(d, r, "float32")
                    for d in (256, 1024) for r in (8, 32)],
}


def fallback_entries():
    """[(kernel, key, config)] rows for the committed default table:
    every DEFAULT_KEYS key mapped to its hand-picked constants with
    source='fallback'. tools/autotune.py --init writes these."""
    out = []
    for kernel, keys in DEFAULT_KEYS.items():
        for key in keys:
            cfg = dict(fallback_config(kernel, key))
            cfg["source"] = "fallback"
            out.append((kernel, key, cfg))
    return out


# ----------------------------------------------------------------------
# analytic roofline (the prune + the stop condition)
# ----------------------------------------------------------------------

def _dtype_bytes(dt):
    import numpy as np

    try:
        return np.dtype(dt).itemsize
    except TypeError:
        return 4


def analytic_cost(kernel, key, config, batch=1, heads=1, causal=True):
    """{flops, bytes} LOWER BOUND for one kernel invocation under
    `config`: the matmul work over the blocks the grid actually
    visits. Block granularity is the point — a causal sweep with big
    key blocks visits (and masks) more dead positions, so its floor
    rises; that is exactly what the prune compares."""
    dims = _dims_of(kernel, key)
    d = dims["d"]
    ib = _dtype_bytes(dims["dtype"])
    bh = batch * heads
    if kernel in ("flash_fwd", "flash_bwd"):
        sq, sk = dims["sq"], dims["sk"]
        bq = min(int(config["block_q"]), sq)
        bk = min(int(config["block_k"]), sk)
        nq = sq // bq
        pairs = 0
        for qi in range(nq):
            if causal and sq == sk:
                pairs += min(math.ceil((qi + 1) * bq / bk), sk // bk)
            else:
                pairs += sk // bk
        # QK^T + PV per visited pair (x2.5 for the bwd's dq/dk/dv
        # recompute stack)
        mm = 4.0 * bq * bk * d * pairs
        if kernel == "flash_bwd":
            mm *= 2.5
        byt = (sq * d + pairs * 2.0 * bk * d) * ib
        return {"flops": bh * mm, "bytes": bh * byt}
    if kernel in ("flash_decode", "flash_verify"):
        L = dims["L"]
        T = dims.get("T", 1)
        n = int(config["split_k"])
        # every split reads its K/V slice; the XLA combine touches
        # n * (T, d) partials
        flops = bh * (4.0 * T * L * d + n * T * (2.0 * d + 8.0))
        byt = bh * (2.0 * L * d * ib + n * T * (d + 2) * 4.0)
        return {"flops": flops, "bytes": byt}
    if kernel == "paged_flash_decode":
        psz = dims["psz"]
        L = psz * 8  # nominal 8 mapped pages; relative cost only
        gather = 0.0 if config.get("kernel", True) else 2.0 * L * d * ib
        return {"flops": bh * 4.0 * L * d,
                "bytes": bh * (2.0 * L * d * ib + gather)}
    if kernel == "paged_flash_verify":
        psz, T = dims["psz"], dims["T"]
        L = psz * 8  # nominal 8 mapped pages; relative cost only
        gather = 0.0 if config.get("kernel", True) else 2.0 * L * d * ib
        return {"flops": bh * 4.0 * T * L * d,
                "bytes": bh * (2.0 * L * d * ib + gather)}
    if kernel == "int8_matmul":
        # nominal decode-batch m = 8 rows; the int8 weight tile is the
        # byte-traffic floor (the whole point of the storage format)
        n = dims["n"]
        m = 8
        return {"flops": bh * 2.0 * m * d * n,
                "bytes": bh * (d * n * 1.0 + n * 4.0 +
                               m * (d + n) * ib)}
    if kernel == "lora_matmul":
        # nominal 8-slot pool, one token per row: two rank-r matmuls
        # per row + the gathered bank rows' traffic
        r = dims["r"]
        m = 8
        gather = 0.0 if config.get("kernel", True) \
            else m * (d * r + r * d) * 4.0
        return {"flops": bh * 2.0 * m * (d * r + r * d),
                "bytes": bh * (m * (d * r + r * d) * 4.0 + gather)}
    raise ValueError(f"unknown kernel {kernel!r}")


def roofline_seconds(cost, spec):
    """The device's floor for a {flops, bytes} cost: compute-bound or
    bandwidth-bound, whichever binds."""
    return max(cost["flops"] / spec.peak_flops,
               cost["bytes"] / spec.peak_bytes_per_s)


def prune(kernel, key, cands, incumbent_s, spec, batch=1, heads=1):
    """Split candidates into (survivors, pruned): a candidate whose
    roofline floor already exceeds the incumbent's MEASURED time can
    never win and is never timed."""
    if incumbent_s is None:
        return list(cands), []
    keep, cut = [], []
    for c in cands:
        floor = roofline_seconds(
            analytic_cost(kernel, key, c, batch, heads), spec)
        (cut if floor > incumbent_s else keep).append(c)
    return keep, cut


# ----------------------------------------------------------------------
# measurement + the sweep driver
# ----------------------------------------------------------------------

def build_runner(kernel, key, config, batch=4, heads=4):
    """Zero-arg timed closure for one (kernel, key, config): jits the
    REAL dispatch path under the candidate config over fixed random
    operands. The sweep measures it with op_bench.measure; the perf
    gate's tuned-vs-fallback rows measure two of these PAIRED with
    op_bench.measure_pair. On non-TPU backends the decode/verify
    dispatchers run their XLA reference (config-invariant there) —
    mechanics still exercise end to end; real block wins need the
    chip."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops import attention as A

    dims = _dims_of(kernel, key)
    d = dims["d"]
    dt = jnp.dtype(dims["dtype"]) if dims["dtype"] != "int8" \
        else jnp.float32
    rs = np.random.RandomState(0)
    if kernel in ("flash_fwd", "flash_bwd"):
        sq, sk = dims["sq"], dims["sk"]
        q = jnp.asarray(rs.randn(batch, heads, sq, d), dt)
        kv = jnp.asarray(rs.randn(batch, heads, sk, d), dt)
        interp = not A._on_tpu()
        bq = min(int(config["block_q"]), sq)
        bk = min(int(config["block_k"]), sk)

        if kernel == "flash_fwd":
            fn = jax.jit(lambda a, b, c: A.flash_attention_fwd(
                a, b, c, None, True, None, bq, bk, interp)[0])
            return lambda: fn(q, kv, kv)
        g = jax.jit(jax.grad(
            lambda a, b, c: A.flash_attention(
                a, b, c, None, True, None, interp, bq, bk)
            .astype(jnp.float32).sum(), (0, 1, 2)))
        return lambda: g(q, kv, kv)
    if kernel in ("flash_decode", "flash_verify"):
        L, T = dims["L"], dims.get("T", 1)
        q = jnp.asarray(rs.randn(batch, heads, T, d), dt)
        kv = jnp.asarray(rs.randn(batch, heads, L, d), dt)
        length = jnp.full((batch,), L, jnp.int32)
        disp = A.verify_attention if kernel == "flash_verify" \
            else A.decode_attention
        fn = jax.jit(lambda a, b, c, n: disp(
            a, b, c, n, split_k=int(config["split_k"])))
        return lambda: fn(q, kv, kv, length)
    if kernel == "paged_flash_verify":
        psz, T = dims["psz"], dims["T"]
        n_pages, mp = 32, 8
        q = jnp.asarray(rs.randn(batch, heads, T, d), jnp.float32)
        pages = jnp.asarray(
            rs.randn(n_pages + 1, heads, psz, d), jnp.float32)
        tbl = jnp.asarray(
            rs.randint(0, n_pages, (batch, mp)), jnp.int32)
        length = jnp.full((batch,), mp * psz, jnp.int32)
        use_kernel = bool(config.get("kernel", True)) and \
            A._on_tpu()   # off-chip, both rows time the gather
        #                   fallback (interpret mode would time the
        #                   emulator, not the kernel)
        if use_kernel:
            fn = jax.jit(lambda a, kp, vp, t, n: A.paged_flash_verify(
                a, kp, vp, None, None, t, n))
        else:
            split = int(config.get("split_k", 0)) or None
            fn = jax.jit(lambda a, kp, vp, t, n: A.verify_attention(
                a, A.paged_gather_kv(kp, None, t, a.dtype),
                A.paged_gather_kv(vp, None, t, a.dtype), n,
                split_k=split))
        return lambda: fn(q, pages, pages, tbl, length)
    if kernel == "int8_matmul":
        from ..ops import quant as Q

        n = dims["n"]
        m = max(8, batch)
        x = jnp.asarray(rs.randn(m, d), dt)
        w = jnp.asarray(rs.randn(d, n) * 0.05, jnp.float32)
        wq, ws = Q.quantize_int8_weight(w)
        bm = int(config.get("block_m", 0)) or None
        bn = int(config.get("block_n", 0)) or None
        # on the CPU harness the dispatcher times the XLA reference
        # (config-invariant); on-chip the explicit blocks pin the
        # candidate tile, same contract as the flash runners
        fn = jax.jit(lambda a, q_, s_: Q.int8_matmul(
            a, q_, s_, block_m=bm, block_n=bn))
        return lambda: fn(x, wq, ws)
    if kernel == "lora_matmul":
        from ..ops import quant as Q

        r = dims["r"]
        n_ad = 4
        x = jnp.asarray(rs.randn(batch, 1, d), dt)
        Ab = jnp.asarray(rs.randn(n_ad, d, r) * 0.05, jnp.float32)
        Bb = jnp.asarray(rs.randn(n_ad, r, d) * 0.05, jnp.float32)
        ids = jnp.asarray(rs.randint(0, n_ad, (batch,)), jnp.int32)
        if bool(config.get("kernel", True)) and A._on_tpu():
            fn = jax.jit(lambda a, wa, wb, i: Q.lora_delta(
                a, wa, wb, i))
        else:
            fn = jax.jit(lambda a, wa, wb, i: Q.lora_delta_reference(
                a, wa, wb, i))
        return lambda: fn(x, Ab, Bb, ids)
    if kernel == "paged_flash_decode":
        psz = dims["psz"]
        n_pages, mp = 32, 8
        q = jnp.asarray(rs.randn(batch, heads, 1, d), jnp.float32)
        pages = jnp.asarray(
            rs.randn(n_pages + 1, heads, psz, d), jnp.float32)
        tbl = jnp.asarray(
            rs.randint(0, n_pages, (batch, mp)), jnp.int32)
        length = jnp.full((batch,), mp * psz, jnp.int32)
        use_kernel = bool(config.get("kernel", True)) and \
            A._on_tpu()   # off-chip, both rows time the gather
        #                   reference (interpret mode would time the
        #                   emulator, not the kernel)
        if use_kernel:
            fn = jax.jit(lambda a, kp, vp, t, n: A.paged_flash_decode(
                a, kp, vp, None, None, t, n))
        else:
            fn = jax.jit(lambda a, kp, vp, t, n:
                         A.decode_attention_reference(
                             a, A.paged_gather_kv(kp, None, t,
                                                  a.dtype),
                             A.paged_gather_kv(vp, None, t,
                                               a.dtype), n))
        return lambda: fn(q, pages, pages, tbl, length)
    raise ValueError(f"unknown kernel {kernel!r}")


def default_measurer(batch=4, heads=4, steps=20, k=5):
    """measurer(kernel, key, config) -> seconds over `build_runner`'s
    real dispatch path, timed with the shared op_bench harness."""
    def measurer(kernel, key, config):
        return _op_bench().measure(
            build_runner(kernel, key, config, batch, heads),
            steps=steps, k=k)

    return measurer


def sweep_key(kernel, key, *, measurer, spec=None, batch=1, heads=1,
              stop_factor=1.1, log=None):
    """Sweep ONE (kernel, key): returns a report dict

        {kernel, key, winner, step_us, fallback, fallback_us,
         timed, pruned, stopped_at_roofline}

    The fallback config is ALWAYS timed first (it is the incumbent the
    prune and the stop condition compare against), so the winner can
    never be slower than the shipped constants *as measured here*."""
    from ..profiler import costs as _costs

    spec = spec if spec is not None else _costs.detect_spec()
    fb = fallback_config(kernel, key)
    t_fb = measurer(kernel, key, fb)
    best, t_best = dict(fb), t_fb
    cands = [c for c in candidates(kernel, key) if c != fb]
    keep, cut = prune(kernel, key, cands, t_fb, spec, batch, heads)
    timed = 1
    stopped = False
    for c in keep:
        floor = roofline_seconds(
            analytic_cost(kernel, key, best, batch, heads), spec)
        if t_best <= stop_factor * floor:
            stopped = True   # incumbent already at the device roofline
            break
        if roofline_seconds(analytic_cost(kernel, key, c, batch,
                                          heads), spec) > t_best:
            cut.append(c)    # incumbent improved past this floor
            continue
        t = measurer(kernel, key, c)
        timed += 1
        if log is not None:
            log(f"  {kernel} {_table.key_str(key)} {c} -> "
                f"{t * 1e6:.1f}us")
        if t < t_best:
            best, t_best = dict(c), t
    report = {"kernel": kernel, "key": _table.key_str(key),
              "winner": best, "step_us": round(t_best * 1e6, 2),
              "fallback": fb, "fallback_us": round(t_fb * 1e6, 2),
              "timed": timed, "pruned": len(cut),
              "stopped_at_roofline": stopped}
    return report


def apply_report(tbl, report, device_kind=None):
    """Install a sweep_key report's winner into `tbl` (device-keyed,
    source='sweep'; the measured step_us rides along for the paper
    trail)."""
    cfg = dict(report["winner"])
    cfg["source"] = "sweep"
    cfg["step_us"] = report["step_us"]
    tbl.put(report["kernel"], report["key"], cfg,
            device_kind=device_kind or _table.current_device_kind())
    return tbl
