"""Kernel autotuning + persistent AOT compilation cache.

Two coupled halves (TVM / Tensor Processing Primitives both argue the
same split — see PAPERS.md):

  * **Autotuner** (`table.py` / `autotune.py`) — pallas block-shape
    configs per (kernel, head_dim, seq bucket, dtype) are *searched*,
    not hand-picked: a sweep times each candidate with the
    `tools/op_bench.py` measurement harness, prunes candidates whose
    analytic roofline lower bound (profiler.costs.DeviceSpec) already
    exceeds the incumbent, and persists winners to a versioned on-disk
    `TuningTable` keyed by `device_kind`. `ops/attention.py` consults
    the table instead of its hard-coded block constants; the committed
    fallback entries equal the hand-picked constants, so CPU/untuned
    devices are bit-identical to the pre-tuning kernels.
  * **Persistent AOT compile cache** (`aot_cache.py`) — at engine
    startup `ServingEngine.precompile()` AOT-lowers-and-compiles every
    serving/prompt-bucket program into `AotCompileCache`, a persisted
    directory with a CRC-manifested index (the CheckpointManager
    staged-rename pattern), so a restarted engine reaches full speed
    with ZERO warmup jit stalls — the retrace sentinel sees no compile
    spans before the first token on a warm start.
"""
from .table import (TuningTable, TableError, get_table, set_table,
                    lookup, reset, current_device_kind,
                    committed_table_path, seq_bucket)
from .aot_cache import AotCompileCache, CacheCorrupt, env_fingerprint

__all__ = [
    "TuningTable", "TableError", "get_table", "set_table", "lookup",
    "reset", "current_device_kind", "committed_table_path",
    "seq_bucket", "AotCompileCache", "CacheCorrupt", "env_fingerprint",
]
