"""Versioned on-disk table of tuned kernel configs, keyed by device.

The flash kernels' block shapes were hand-picked constants
(`_pick_blocks`'s 512-first ladder, `_pick_decode_splits`'s ~512-token
splits). This table makes them *data*: `ops/attention.py` consults
`tuning.lookup(kernel, key)` at trace time and falls back to the old
heuristics on a miss — the committed default table's entries equal the
heuristic outputs exactly (tests pin this), so an untuned device is
bit-identical to the pre-tuning kernels, and a device-specific sweep
(tools/autotune.py) can override them without touching kernel code.

Schema (JSON, atomic tmp+os.replace writes):

    {"version": 1,
     "devices": {
       "any":      {"flash_fwd": {"d64/sq1024/sk1024/float32":
                                  {"block_q": 512, "block_k": 512,
                                   "source": "fallback"}}},
       "TPU v5e":  {"flash_decode": {"d64/L2048/float32":
                                     {"split_k": 4, "step_us": 41.2,
                                      "source": "sweep"}}}}}

Lookup order: exact `device_kind` first, then the `"any"` tier (the
committed fallback entries live there). Key tuples are joined with
"/" — sequence lengths are bucketed to powers of two (`seq_bucket`)
so the table stays O(log n) rows per kernel.

Kernels and their tunable knobs:

    flash_fwd / flash_bwd   {"block_q", "block_k"}   (fwd and bwd tune
                            independently; bwd defaults to fwd blocks)
    flash_decode            {"split_k"}
    flash_verify            {"split_k"}
    paged_flash_decode      {"kernel": bool}  — dispatch-level: force
                            the XLA gather path on devices where the
                            scalar-prefetch kernel loses (the grid is
                            (slot*head, page): no shape knob exists)
    paged_flash_verify      {"kernel": bool, "split_k"} — the paged
                            speculative verify: kernel-on (grid fixed
                            by the pages) or gather + the dense verify
                            dispatch at the tuned split_k
    int8_matmul             {"block_m", "block_n"} — the scaled-int8
                            weight matmul's tile shape (keys
                            (d_in bucket, d_out bucket, dtype))
    lora_matmul             {"kernel": bool} — dispatch-level: the
                            gathered-LoRA scalar-prefetch kernel vs
                            the XLA gathered einsum (keys
                            (d bucket, rank, dtype))

Env switches: ``PT_TUNING=0`` disables every lookup (pure heuristics,
zero table reads); ``PT_TUNING_TABLE=/path.json`` layers an extra
table over the committed default (its entries win).
"""
from __future__ import annotations

import json
import os
import threading

__all__ = ["TuningTable", "TableError", "KERNELS", "seq_bucket",
           "get_table", "set_table", "lookup", "reset",
           "current_device_kind", "committed_table_path"]

KERNELS = ("flash_fwd", "flash_bwd", "flash_decode", "flash_verify",
           "paged_flash_decode", "paged_flash_verify", "int8_matmul",
           "lora_matmul")

#: knob names each kernel's config may carry (schema validation:
#: unknown keys are tolerated — forward compat — but a config missing
#: every knob is meaningless and rejected at put() time)
KERNEL_KNOBS = {
    "flash_fwd": ("block_q", "block_k"),
    "flash_bwd": ("block_q", "block_k"),
    "flash_decode": ("split_k",),
    "flash_verify": ("split_k",),
    "paged_flash_decode": ("kernel",),
    "paged_flash_verify": ("kernel", "split_k"),
    "int8_matmul": ("block_m", "block_n"),
    "lora_matmul": ("kernel",),
}

#: bump when the key layout or knob semantics change: a mismatched
#: table is IGNORED (heuristic fallback), never misread
TABLE_VERSION = 1


class TableError(ValueError):
    """Malformed / version-mismatched tuning table."""


def seq_bucket(n):
    """Power-of-two bucket for sequence-length key components (same
    policy as core.bucketing.bucket_size, inlined so the table has no
    package dependencies)."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def key_str(parts):
    """Canonical string form of a key tuple: 'd64/sq1024/float32'."""
    if isinstance(parts, str):
        return parts
    return "/".join(str(p) for p in parts)


def current_device_kind():
    """jax's device_kind for the default device ('cpu' fallback) —
    the table's device tier."""
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "cpu"


class TuningTable:
    """{device_kind: {kernel: {key_str: config}}} with atomic JSON
    persistence. Thread-safe for concurrent lookup/put (the serving
    engines consult it at trace time)."""

    def __init__(self, devices=None):
        self._lock = threading.Lock()
        self._devices = {}
        for dev, kernels in (devices or {}).items():
            for kern, entries in kernels.items():
                for k, cfg in entries.items():
                    self.put(kern, k, cfg, device_kind=dev,
                             _validate=False)

    # ---- access ----
    def lookup(self, kernel, key, device_kind=None):
        """The tuned config for (kernel, key) — exact device tier
        first, then 'any'. Returns None on a miss (caller falls back
        to its heuristic)."""
        ks = key_str(key)
        if device_kind is None:
            device_kind = current_device_kind()
        with self._lock:
            for tier in (device_kind, "any"):
                cfg = self._devices.get(tier, {}).get(kernel, {}) \
                    .get(ks)
                if cfg is not None:
                    return dict(cfg)
        return None

    def put(self, kernel, key, config, device_kind="any",
            _validate=True):
        """Install one entry. `config` keeps extra metadata fields
        (step_us, source, ...) alongside the knobs."""
        if _validate:
            if kernel not in KERNELS:
                raise TableError(f"unknown kernel {kernel!r} (one of "
                                 f"{KERNELS})")
            knobs = KERNEL_KNOBS[kernel]
            if not any(k in config for k in knobs):
                raise TableError(
                    f"config for {kernel!r} names none of its knobs "
                    f"{knobs}: {config!r}")
        with self._lock:
            self._devices.setdefault(str(device_kind), {}) \
                .setdefault(str(kernel), {})[key_str(key)] = dict(config)

    def merge(self, other):
        """Layer `other`'s entries over this table (other wins)."""
        for dev, kernels in other.as_dict()["devices"].items():
            for kern, entries in kernels.items():
                for k, cfg in entries.items():
                    self.put(kern, k, cfg, device_kind=dev,
                             _validate=False)
        return self

    def entries(self, device_kind=None, kernel=None):
        """Flat [(device_kind, kernel, key_str, config)] rows (the CLI
        renders these)."""
        out = []
        with self._lock:
            for dev, kernels in sorted(self._devices.items()):
                if device_kind is not None and dev != device_kind:
                    continue
                for kern, ent in sorted(kernels.items()):
                    if kernel is not None and kern != kernel:
                        continue
                    for k, cfg in sorted(ent.items()):
                        out.append((dev, kern, k, dict(cfg)))
        return out

    def __len__(self):
        return len(self.entries())

    # ---- persistence ----
    def as_dict(self):
        with self._lock:
            return {"version": TABLE_VERSION,
                    "devices": {d: {k: {kk: dict(c)
                                        for kk, c in e.items()}
                                    for k, e in kernels.items()}
                                for d, kernels in self._devices.items()}}

    def save(self, path):
        """Atomic write: tmp in the target dir, then os.replace — a
        torn write can never leave a half-table behind (the
        CheckpointManager staging discipline)."""
        payload = json.dumps(self.as_dict(), indent=1, sort_keys=True)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".{os.path.basename(path)}.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, path):
        """Parse + version-check a table file. Raises TableError on a
        malformed/mismatched file — get_table() catches it and falls
        back to heuristics with a warning, never crashing a serve."""
        try:
            with open(path) as f:
                raw = json.load(f)
        except (OSError, ValueError) as e:
            raise TableError(f"unreadable tuning table {path}: {e}")
        if not isinstance(raw, dict) or \
                raw.get("version") != TABLE_VERSION:
            raise TableError(
                f"tuning table {path} version "
                f"{raw.get('version') if isinstance(raw, dict) else '?'}"
                f" != {TABLE_VERSION}")
        devices = raw.get("devices")
        if not isinstance(devices, dict):
            raise TableError(f"tuning table {path} has no devices map")
        return cls(devices)


# ----------------------------------------------------------------------
# the module-wide table the kernels consult
# ----------------------------------------------------------------------

def committed_table_path():
    """The in-repo default table (fallback entries == the hand-picked
    constants; sweeps merge device tiers into it via tools/autotune.py
    --merge)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tables", "default.json")


_LOCK = threading.Lock()
_UNSET = object()
_TABLE = _UNSET
_WARNED = set()


def _warn_once(tag, msg):
    if tag in _WARNED:
        return
    _WARNED.add(tag)
    import warnings

    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _load_default():
    table = TuningTable()
    try:
        table.merge(TuningTable.load(committed_table_path()))
    except TableError as e:
        _warn_once("default", f"committed tuning table unusable "
                              f"({e}); kernel heuristics apply")
    extra = os.environ.get("PT_TUNING_TABLE")
    if extra:
        try:
            table.merge(TuningTable.load(extra))
        except TableError as e:
            _warn_once("env", f"PT_TUNING_TABLE unusable ({e}); "
                              f"entry ignored")
    return table


def get_table():
    """The active TuningTable (lazily loaded; None when PT_TUNING=0)."""
    global _TABLE
    if os.environ.get("PT_TUNING", "1") == "0":
        return None
    t = _TABLE
    if t is _UNSET:
        with _LOCK:
            if _TABLE is _UNSET:
                _TABLE = _load_default()
            t = _TABLE
    return t


def set_table(table):
    """Install a table explicitly (tests / after a sweep). None means
    re-load lazily on next use."""
    global _TABLE
    with _LOCK:
        _TABLE = table if table is not None else _UNSET


def reset():
    """Back to lazy default loading (test teardown symmetry)."""
    set_table(None)


def lookup(kernel, key, device_kind=None):
    """The one call sites make: tuned config dict, or None (use the
    heuristic). One env read + two dict hits on the hot path; returns
    None unconditionally under PT_TUNING=0."""
    t = get_table()
    if t is None:
        return None
    return t.lookup(kernel, key, device_kind=device_kind)
