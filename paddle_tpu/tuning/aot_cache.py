"""Persistent AOT compilation cache: serialized XLA executables on disk.

Every engine restart used to pay the full jit-warmup tax — one
trace+compile per serving program (join per prompt bucket, the batched
decode step, the spec draft/verify pair, the paged attach/cow) before
the first token could flow. `AotCompileCache` persists each compiled
program (via `jax.experimental.serialize_executable`) into a cache
directory with a CRC-manifested index, so `ServingEngine.precompile()`
on a restarted server *deserializes* every program instead of
recompiling it: the retrace sentinel sees ZERO compile spans before
the first token.

Layout (all writes staged tmp + os.replace — the CheckpointManager
atomicity discipline; a torn write can never leave a half entry that
parses):

    <dir>/MANIFEST.json          {"version", "fingerprint", "entries":
                                  {digest: {"key", "crc32", "size"}}}
    <dir>/entries/<digest>.bin   pickle((payload, in_tree, out_tree))

Robustness contract (chaos-tested): a torn/corrupt entry (CRC
mismatch), a version- or environment-mismatched manifest, or an
unpicklable blob NEVER crashes startup — the entry counts as a miss
(`stats["corrupt"]` / `stats["stale"]`), the program compiles fresh,
and a store refreshes the entry. The `tuning.cache_load` fault point
lets tests corrupt the blob in flight.

Cache identity: entries are only valid for the exact environment that
wrote them — `env_fingerprint()` pins jax/jaxlib versions, backend,
device kind and device count; the engines additionally fold a model
fingerprint (param/buffer names, shapes, dtypes) and the pool config
into each entry key, so two different models sharing one cache dir
can never collide.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import zlib

from ..testing import faults

__all__ = ["AotCompileCache", "CacheCorrupt", "env_fingerprint",
           "model_fingerprint"]

#: armed by chaos tests to corrupt/raise/delay on every cache-entry
#: read (payload = the raw entry bytes, pre-CRC-check)
_PT_CACHE_LOAD = faults.point("tuning.cache_load")

#: bump when the entry payload format changes: old caches read as
#: stale (recompile + overwrite), never as garbage
CACHE_SCHEMA = 1


class CacheCorrupt(RuntimeError):
    """A cache entry failed its CRC / unpickle — internal signal; the
    public load() surface converts it into a miss + counter."""


def env_fingerprint():
    """Everything a serialized executable is only valid for."""
    import jax
    import jaxlib

    try:
        devs = jax.devices()
        kind, n = devs[0].device_kind, len(devs)
    except Exception:
        kind, n = "unknown", 0
    return {"schema": CACHE_SCHEMA,
            "jax": jax.__version__,
            "jaxlib": jaxlib.__version__,
            "backend": jax.default_backend(),
            "device_kind": kind,
            "n_devices": n}


def model_fingerprint(params, buffers=None):
    """sha256 over sorted (name, shape, dtype) of a param/buffer set:
    two models with different weight SHAPES can never share an entry
    (values don't matter — weights are runtime arguments)."""
    h = hashlib.sha256()
    for tree in (params, buffers or {}):
        for name in sorted(tree):
            v = tree[name]
            v = getattr(v, "_data", v)
            h.update(f"{name}:{getattr(v, 'shape', ())}:"
                     f"{getattr(v, 'dtype', '?')};".encode())
    return h.hexdigest()[:16]


class AotCompileCache:
    """One cache directory. Thread-safe; counters in `stats` make the
    cold-start metrics exact:

        loaded   entries deserialized (no compile paid)
        saved    entries written
        misses   keys with no (valid) entry
        corrupt  CRC/unpickle failures that fell back to compile
        stale    manifest version/fingerprint mismatches discarded
    """

    MANIFEST = "MANIFEST.json"

    def __init__(self, path):
        self.path = str(path)
        self._entries_dir = os.path.join(self.path, "entries")
        self._lock = threading.Lock()
        self._fp = env_fingerprint()
        self.stats = {"loaded": 0, "saved": 0, "misses": 0,
                      "corrupt": 0, "stale": 0}
        self._manifest = self._read_manifest()

    # ---- manifest ----
    def _manifest_path(self):
        return os.path.join(self.path, self.MANIFEST)

    def _read_manifest(self):   # analysis: single-threaded
        # construction-time only: no second thread can hold the cache
        # while __init__ is still populating it
        try:
            with open(self._manifest_path()) as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return {}
        if not isinstance(raw, dict) or \
                raw.get("fingerprint") != self._fp:
            # another jax/device/schema wrote this cache: every entry
            # is unloadable here — start empty; stores will rebuild
            # the manifest under the current fingerprint
            if isinstance(raw, dict) and raw.get("entries"):
                self.stats["stale"] += len(raw["entries"])
            return {}
        ent = raw.get("entries")
        return dict(ent) if isinstance(ent, dict) else {}

    def _write_manifest(self):
        os.makedirs(self.path, exist_ok=True)
        payload = json.dumps({"version": CACHE_SCHEMA,
                              "fingerprint": self._fp,
                              "entries": self._manifest},
                             indent=1, sort_keys=True)
        tmp = os.path.join(self.path,
                           f".{self.MANIFEST}.tmp-{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, self._manifest_path())

    @staticmethod
    def _digest(key_str):
        return hashlib.sha256(key_str.encode()).hexdigest()[:32]

    def __len__(self):
        with self._lock:
            return len(self._manifest)

    def keys(self):
        with self._lock:
            return sorted(m["key"] for m in self._manifest.values())

    # ---- load / store ----
    def load(self, key_str):
        """The deserialized executable for `key_str`, or None (miss /
        corrupt / stale — counted, never raised)."""
        dg = self._digest(key_str)
        with self._lock:
            meta = self._manifest.get(dg)
        if meta is None or meta.get("key") != key_str:
            with self._lock:
                self.stats["misses"] += 1
            return None
        try:
            with open(os.path.join(self._entries_dir, dg + ".bin"),
                      "rb") as f:
                blob = f.read()
            blob = _PT_CACHE_LOAD(payload=blob)
            if zlib.crc32(blob) != meta.get("crc32") or \
                    len(blob) != meta.get("size"):
                raise CacheCorrupt(
                    f"entry {dg} failed its CRC/size check "
                    f"(torn write or bit rot)")
            payload, in_tree, out_tree = pickle.loads(blob)
            from jax.experimental import serialize_executable as se

            out = se.deserialize_and_load(payload, in_tree, out_tree)
            with self._lock:
                self.stats["loaded"] += 1
            return out
        except faults.InjectedFault:
            raise
        except Exception:
            # torn entry / undeserializable executable: drop it from
            # the manifest so the refreshed store isn't shadowed
            with self._lock:
                self.stats["corrupt"] += 1
                self._manifest.pop(dg, None)
                try:
                    self._write_manifest()
                except OSError:
                    pass
            return None

    def store(self, key_str, compiled):
        """Serialize + persist one compiled program. Returns True on
        success; False (counted nowhere fatal) when this executable
        type can't serialize (e.g. some multi-device assemblies) or
        the disk write fails — precompile still proceeded, only the
        NEXT start pays that program's compile again."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            return False
        dg = self._digest(key_str)
        try:
            os.makedirs(self._entries_dir, exist_ok=True)
            tmp = os.path.join(self._entries_dir,
                               f".{dg}.tmp-{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self._entries_dir,
                                         dg + ".bin"))
            with self._lock:
                self._manifest[dg] = {"key": key_str,
                                      "crc32": zlib.crc32(blob),
                                      "size": len(blob)}
                self._write_manifest()
        except OSError:
            return False
        with self._lock:
            self.stats["saved"] += 1
        return True
