"""Benchmarks for the BASELINE.md configs, single chip.

Prints ONE JSON line (the headline ERNIE-base fine-tune throughput,
config 3) to stdout; every config's result is also written to
BENCH_DETAILS.json and echoed to stderr:

  1. fluid static-graph MNIST (LeNet, whole-block XLA Executor)  imgs/s
  2. paddle.vision ResNet-50 (dygraph functionalized, bf16)      imgs/s
  3. ERNIE-base fine-tune (bf16)                                 seq/s
  5. CTR-DNN, async native PS, unique-row bf16 wire              ex/s
  +  long_context: pallas flash vs XLA attention kernel A/B      x
  +  ernie_long:   seq-1024 fine-tune, default vs flash-forced   seq/s
                   (+ a seq-4096 row, flash vs XLA, dropout on)
  +  packed_varlen: LoD-packed segment-id flash vs padded-dense
                   fine-tune at ~50% fill                        seq/s
  +  fused_optimizer: fused vs per-param opt.step() A/B (Adam +
                   global-norm clip, ~200 small tensors)         x
  +  decode_throughput: fused static-KV-cache decode scan vs
                   eager concat-cache generation loop, tokens/s  x
  4. multichip_scaling: allreduce busbw + DP weak scaling — runs
     whenever >1 device is visible (records skipped on this 1-chip
     host; validated on the 8-device CPU mesh by the test suite).

vs_baseline for the headline is measured against a provisional 300 seq/s
target — the paddlepaddle-gpu BERT-base fp16 fine-tune per-V100-chip
class the north star asks us to match (BASELINE.json has no published
numbers; see BASELINE.md).
"""
from __future__ import annotations

import contextlib
import functools
import json
import sys
import time

import numpy as np

TARGET_SEQ_PER_SEC = 300.0

#: --trace: serving benches run under a tracer session and write a
#: chrome-trace artifact per run (tools/trace_report.py / Perfetto)
_TRACE = False


@contextlib.contextmanager
def _maybe_trace(tag):
    """Wrap a serving-bench drive in a tracer session when --trace is
    set; exports /tmp/paddle_tpu_trace_<tag>.json. Yields the artifact
    path holder (path at [0] after exit) so results can record it."""
    holder = [None]
    if not _TRACE:
        yield holder
        return
    from paddle_tpu.profiler import trace as T

    tr = T.start_session(capacity=1 << 18)
    try:
        yield holder
    finally:
        T.end_session()
        holder[0] = tr.export_chrome_trace(
            f"/tmp/paddle_tpu_trace_{tag}.json")
        print(f"# trace artifact: {holder[0]}", file=sys.stderr)

STEPS = 50


def _marginal_step_time(run_n, steps, lo_frac=5):
    """Per-step time via two-point marginal measurement.

    run_n(n) must execute an n-step jitted loop end-to-end (bounded by a
    host readback) and return its wall time; it is called warm. The
    marginal slope (t_hi - t_lo) / (steps - lo) cancels the fixed
    dispatch+readback latency of a tunneled/remote chip runtime — which is
    seconds-noisy and not model throughput. Falls back to plain t/steps
    (conservative) when noise wins or the two points coincide.
    """
    lo = max(2, steps // lo_frac)
    if lo >= steps:  # degenerate: single point, single measurement
        run_n(steps)
        dt = run_n(steps) / steps
        return dt, dt, [dt]
    for n in (steps, lo):
        run_n(n)  # compile + warm this n
    # measure ADJACENT (lo, hi) pairs and take the MEDIAN of per-pair
    # slopes: pairing cancels the tunnel's slow drift (each pair sees
    # nearly the same fixed overhead), and the median resists the
    # multi-second outliers that bias a min-of-points estimator in
    # EITHER direction (min-based slopes measured 1.7x above the
    # device-profile truth under asymmetric noise)
    slopes = []
    t_hi_best = None
    for _ in range(7):
        t_lo = run_n(lo)
        t_hi = run_n(steps)
        t_hi_best = t_hi if t_hi_best is None else min(t_hi_best, t_hi)
        if t_hi > t_lo:
            slopes.append((t_hi - t_lo) / (steps - lo))
    if not slopes:
        return t_hi_best / steps, t_hi_best / steps, [t_hi_best / steps]
    slopes.sort()
    dt = slopes[len(slopes) // 2]
    return dt, t_hi_best / steps, slopes


def _spread(per_sample_values, kind="pair_slopes"):
    """Dispersion record for per-sample throughput estimates: the
    headline is the MEDIAN (driver-reproducible), and the spread states
    how far one observed sample can land from it (VERDICT r03 weak #2:
    single-trial numbers drifted 28% run-to-run unflagged). `kind`
    keeps the record honest about sample independence: 'pair_slopes'
    are adjacent-pair marginal slopes (noise-negative pairs dropped,
    so the sample is censored and correlated); 'trials' are fully
    independent end-to-end repetitions."""
    vs = sorted(float(v) for v in per_sample_values)
    med = vs[len(vs) // 2]
    lo, hi = vs[0], vs[-1]
    return {"samples": len(vs), "kind": kind,
            "min": round(lo, 2), "max": round(hi, 2),
            "spread_pct": round(100.0 * (hi - lo) / med, 1) if med else 0.0}



def _softmax_ce(logits, labels):
    """Shared bench loss: f32 log-softmax CE over integer labels."""
    import jax
    import jax.numpy as jnp

    lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.take_along_axis(lp, labels[:, None], -1).mean()


def _ernie(batch=32, seq_len=128, steps=STEPS, layers=12, hidden=768, heads=12, inter=3072):
    """Config 3 headline. r04 device profile (xprof op_profile, 20-step
    warm window): matmul-bearing fusions 71.8% of device time, big
    elementwise loop fusions (layernorm/dropout/residual chains) 9.8%,
    async copy-done 9.0% (XLA memory-space copies around the step-scan
    carries), rng 2.3%, data-formatting 1.8% — ~58% MFU with no single
    recoverable hotspot left; further gains would need fused-layernorm
    kernels of marginal value."""
    import jax

    import paddle_tpu  # noqa: F401
    from paddle_tpu.optimizer import functional as fopt
    from paddle_tpu.parallel import SpmdTrainer, init_mesh
    from paddle_tpu.text import ErnieConfig, ErnieForSequenceClassification

    BATCH, SEQ_LEN = batch, seq_len
    dev = jax.devices()[0]
    mesh = init_mesh(dp=1, devices=[dev])
    cfg = ErnieConfig(vocab_size=30522, hidden_size=hidden,
                      num_layers=layers, num_heads=heads,
                      intermediate_size=inter,
                      max_position=SEQ_LEN + 2, hidden_dropout=0.1,
                      num_classes=2)
    net = ErnieForSequenceClassification(cfg)

    ce = _softmax_ce

    tr = SpmdTrainer(net, ce, fopt.adamw(5e-5), mesh=mesh,
                     compute_dtype="bfloat16")
    rs = np.random.RandomState(0)
    ids = rs.randint(1, cfg.vocab_size, (BATCH, SEQ_LEN)).astype(np.int64)
    labels = rs.randint(0, 2, (BATCH,)).astype(np.int64)
    key = jax.random.PRNGKey(0)
    ids, labels = tr.shard_batch(ids, labels)

    # one jitted multi-step lax.scan per point; the float() readback bounds
    # completion (async-dispatch runtimes under-report otherwise)
    def run_n(n):
        t0 = time.perf_counter()
        lf = float(tr.run_steps((ids,), labels, n, rng=key))
        dt = time.perf_counter() - t0
        assert lf == lf, "ERNIE produced NaN loss"
        return dt

    dt, dt_e2e, slopes = _marginal_step_time(run_n, steps)
    v = BATCH / dt
    return {"metric": "ernie_base_finetune_seq_per_sec_per_chip",
            "value": round(v, 2), "unit": "seq/s",
            "vs_baseline": round(v / TARGET_SEQ_PER_SEC, 3),
            "e2e_value": round(BATCH / dt_e2e, 2),
            "spread": _spread([BATCH / s for s in slopes]),
            "method": "two-point marginal over jitted multi-step scans "
                      "(fixed remote-dispatch latency excluded; e2e_value "
                      "keeps it included)"}


def _ernie_long(batch=8, seq_len=1024, steps=16):
    """Long-context ERNIE fine-tune (seq 1024) WITH dropout 0.1 (the
    realistic fine-tune config): the default dispatch — the pallas
    flash kernel with IN-KERNEL counter-addressed prob-dropout — vs the
    XLA fused path forced on. This is the full-model companion to the
    `long_context` kernel A/B, and the measurement that SET the
    dispatch default: the r05 kernel (512x512 blocks, diagonal-split
    causal, scale folded into the q block) wins in-model 1.22x at
    dropout 0 and ~1.56x at dropout 0.1, where the XLA path pays RNG +
    HBM for the full [B,H,S,S] prob tensor. r04's kernel lost in-model
    (0.94x) and had no dropout at all — both VERDICT r04 items.

    Also measures a seq4096 row (smaller batch, same dropout-0.1
    config): the standalone kernel numbers promise ~3.2x at 4096 but
    the in-model bench never showed it — this records what the model
    actually sees at long context (flash vs XLA-forced)."""
    import os

    def measure(force_xla, dropout, seq=seq_len, bsz=batch,
                nsteps=steps):
        import jax

        if force_xla:
            os.environ["PT_FLASH_MIN_SEQ_BSHD"] = "999999"
            os.environ["PT_FLASH_MIN_SEQ_BSHD_DROP"] = "999999"
        else:
            os.environ.pop("PT_FLASH_MIN_SEQ_BSHD", None)
            os.environ.pop("PT_FLASH_MIN_SEQ_BSHD_DROP", None)
        from paddle_tpu.optimizer import functional as fopt
        from paddle_tpu.parallel import SpmdTrainer, init_mesh
        from paddle_tpu.text import (ErnieConfig,
                                     ErnieForSequenceClassification)

        mesh = init_mesh(dp=1, devices=[jax.devices()[0]])
        cfg = ErnieConfig(vocab_size=30522, max_position=seq + 2,
                          hidden_dropout=dropout, attn_dropout=dropout,
                          num_classes=2)
        net = ErnieForSequenceClassification(cfg)

        ce = _softmax_ce

        tr = SpmdTrainer(net, ce, fopt.adamw(5e-5), mesh=mesh,
                         compute_dtype="bfloat16")
        rs = np.random.RandomState(0)
        ids = rs.randint(1, cfg.vocab_size,
                         (bsz, seq)).astype(np.int64)
        labels = rs.randint(0, 2, (bsz,)).astype(np.int64)
        key = jax.random.PRNGKey(0)
        dids, dlabels = tr.shard_batch(ids, labels)

        def run_n(n):
            t0 = time.perf_counter()
            lf = float(tr.run_steps((dids,), dlabels, n, rng=key))
            dt = time.perf_counter() - t0
            assert lf == lf, "ernie_long produced NaN loss"
            return dt

        dt, _, slopes = _marginal_step_time(run_n, nsteps, lo_frac=4)
        return bsz / dt, slopes

    saved = {k: os.environ.get(k) for k in
             ("PT_FLASH_MIN_SEQ_BSHD", "PT_FLASH_MIN_SEQ_BSHD_DROP")}
    try:
        v_default, slopes = measure(False, 0.1)   # flash, dropout on
        v_xla, _ = measure(True, 0.1)             # XLA forced
        v_def0, _ = measure(False, 0.0)           # flash, dropout off
        v_xla0, _ = measure(True, 0.0)
        # seq4096 row: dropout on, flash vs XLA-forced (batch scaled
        # down 4x so the [B,H,S,S] prob tensor of the FORCED XLA run
        # still fits HBM; seq/s stays comparable per chip)
        v4k_fl, _ = measure(False, 0.1, seq=4096, bsz=max(batch // 4, 1),
                            nsteps=max(steps // 2, 4))
        v4k_xla, _ = measure(True, 0.1, seq=4096, bsz=max(batch // 4, 1),
                             nsteps=max(steps // 2, 4))
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {"metric": "ernie_long_context_seq1024_seq_per_sec_per_chip",
            "value": round(v_default, 2), "unit": "seq/s",
            "xla_forced_seq_per_sec": round(v_xla, 2),
            "flash_vs_default": round(v_default / v_xla, 3),
            "dropout_off": {"flash": round(v_def0, 2),
                            "xla": round(v_xla0, 2),
                            "ratio": round(v_def0 / v_xla0, 3)},
            "seq4096": {"flash": round(v4k_fl, 2),
                        "xla": round(v4k_xla, 2),
                        "ratio": round(v4k_fl / v4k_xla, 3),
                        "config": {"batch": max(batch // 4, 1),
                                   "seq_len": 4096, "dropout": 0.1}},
            "spread": _spread([batch / s for s in slopes]),
            "config": {"batch": batch, "seq_len": seq_len,
                       "dropout": 0.1,
                       "note": "dropout 0.1 incl. attention probs via "
                               "the IN-KERNEL flash dropout (counter-"
                               "addressed bits); default dispatch IS "
                               "the flash path since r05 (see "
                               "sdpa_bshd docstring)"},
            "method": "two-point marginal over jitted multi-step scans"}


def _packed_varlen(batch=16, max_len=1024, steps=12, hidden=768,
                   layers=12, heads=12, inter=3072):
    """Packed (LoD-native segment ids) vs padded-dense ERNIE fine-tune
    A/B at a realistic ~50% fill length mix. Both runs train the SAME
    number of sequences per step through the full base model with
    dropout 0.1; the padded run feeds [batch, max_len] rows plus a
    padding mask (the kv-bias flash path), the packed run feeds
    core/lod.pack_padded rows — several sequences back-to-back per row,
    segment ids routed to the segment-masked flash kernel whose
    block-level early-out also skips cross-segment work. The win
    compounds: ~2x fewer rows at 50% fill times the kernel's skipped
    blocks, so packed/padded should approach 2x."""
    import jax

    import paddle_tpu  # noqa: F401
    from paddle_tpu import nn
    from paddle_tpu.core.lod import pack_padded
    from paddle_tpu.optimizer import functional as fopt
    from paddle_tpu.parallel import SpmdTrainer, init_mesh
    from paddle_tpu.text import ErnieConfig, ErnieForSequenceClassification

    rs = np.random.RandomState(0)
    # ~50% fill: lengths uniform in [max_len/16, max_len], mean ~0.53
    lens = np.sort(rs.randint(max_len // 16, max_len + 1, size=batch))
    ids = np.zeros((batch, max_len), np.int64)
    mask = np.zeros((batch, max_len), np.float32)
    vocab = 30522
    for b, n in enumerate(lens):
        ids[b, :n] = rs.randint(1, vocab, n)
        mask[b, :n] = 1.0
    labels = rs.randint(0, 2, (batch,)).astype(np.int64)
    pk = pack_padded(ids, lens, row_len=max_len)

    def cfg_for(rows):
        return ErnieConfig(vocab_size=vocab, max_position=max_len + 2,
                           hidden_size=hidden, num_layers=layers,
                           num_heads=heads, intermediate_size=inter,
                           hidden_dropout=0.1, attn_dropout=0.1,
                           num_classes=2)

    class _PackedErnie(nn.Layer):
        """Positional-arg adapter: SpmdTrainer feeds net(*inputs)."""

        def __init__(self, cfg):
            super().__init__()
            self.inner = ErnieForSequenceClassification(cfg)

        def forward(self, ids, positions, segs, cls_idx):
            return self.inner(ids, position_ids=positions,
                              attn_segment_ids=segs,
                              cls_flat_index=cls_idx)

    def measure(net, inputs):
        mesh = init_mesh(dp=1, devices=[jax.devices()[0]])
        tr = SpmdTrainer(net, _softmax_ce, fopt.adamw(5e-5), mesh=mesh,
                         compute_dtype="bfloat16")
        key = jax.random.PRNGKey(0)
        data = tr.shard_batch(*inputs, labels)
        dins, dlabels = data[:-1], data[-1]

        def run_n(n):
            t0 = time.perf_counter()
            lf = float(tr.run_steps(dins, dlabels, n, rng=key))
            dt = time.perf_counter() - t0
            assert lf == lf, "packed_varlen produced NaN loss"
            return dt

        dt, _, slopes = _marginal_step_time(run_n, steps, lo_frac=4)
        return batch / dt, slopes

    ttype = np.zeros((batch, max_len), np.int64)
    v_padded, _ = measure(ErnieForSequenceClassification(cfg_for(batch)),
                          (ids, ttype, mask))
    v_packed, slopes = measure(
        _PackedErnie(cfg_for(pk.num_rows)),
        (pk.data.astype(np.int64), pk.positions.astype(np.int64),
         pk.segment_ids, pk.cls_flat_index().astype(np.int64)))
    return {"metric": "packed_varlen_seq_per_sec_per_chip",
            "value": round(v_packed, 2), "unit": "seq/s",
            "padded_seq_per_sec": round(v_padded, 2),
            "packed_vs_padded": round(v_packed / v_padded, 3),
            "spread": _spread([batch / s for s in slopes]),
            "config": {"sequences": batch, "max_len": max_len,
                       "packed_rows": pk.num_rows,
                       "fill": round(pk.fill, 3), "dropout": 0.1,
                       "note": "padded = kv-bias flash path on "
                               "[batch, max_len] rows; packed = "
                               "segment-masked flash on pack_padded "
                               "rows (block-level early-out), CLS "
                               "pooled per sequence via flat gather"},
            "method": "two-point marginal over jitted multi-step scans"}


def _hbm_profile():
    """Measure usable HBM bandwidth: a chained elementwise loop over a
    205MB bf16 tensor (reads+writes once per iteration), timed via the
    two-point marginal. Elementwise fusions are pure HBM streams, so
    bytes/time is the achievable roofline."""
    import jax
    import jax.lax as lax
    import jax.numpy as jnp

    x = jnp.asarray(np.random.RandomState(0)
                    .randn(128, 256, 56, 56) * 0.1, jnp.bfloat16)

    @jax.jit
    def run(x, n):
        return lax.fori_loop(
            0, n, lambda i, x: x * jnp.bfloat16(1.0000001)
            + jnp.bfloat16(1e-7), x)

    def run_n(n):
        t0 = time.perf_counter()
        float(run(x, n).ravel()[0])
        return time.perf_counter() - t0

    # median-of-pairs marginal (the min-of-2 estimator is biased under
    # this tunnel's asymmetric noise — see _marginal_step_time)
    dt, _, _ = _marginal_step_time(run_n, 60, lo_frac=6)
    return x.nbytes * 2 / max(dt, 1e-6)  # bytes/s


def _resnet50_min_traffic(batch):
    """Analytic lower bound on HBM bytes per training step, bf16
    activations: per conv, fwd reads the input activation and writes the
    output twice-read (once by the fused BN-stats reduce, once by the
    next layer via the normalize folded into its prologue); bwd reads
    dy + saved input for the weight grad, dy + weights for the data
    grad, writes dx, and re-reads the output for the relu mask.
    ~= 3*in + 5*out bytes per conv at 2B/elem. Stem/pool/fc + fp32
    param/momentum update traffic added explicitly."""
    # (in_c, in_hw, out_c, out_hw) with input sizes tracked explicitly —
    # channel counts collide across resolutions, so no c->hw lookup
    convs = [(3, 224, 64, 112)]                  # stem
    cfg = [(3, 64, 256, 56), (4, 128, 512, 28),
           (6, 256, 1024, 14), (3, 512, 2048, 7)]
    cin, hw_cur = 64, 56                         # after stem maxpool
    for n, cmid, cout, hw in cfg:
        for b in range(n):
            convs.append((cin, hw_cur, cmid, hw_cur))      # 1x1 reduce
            convs.append((cmid, hw_cur, cmid, hw))         # 3x3 (strides)
            convs.append((cmid, hw, cout, hw))             # 1x1 expand
            if b == 0:
                convs.append((cin, hw_cur, cout, hw))      # projection
            cin, hw_cur = cout, hw
    total = 0
    for ci, hi, co, ho in convs:
        in_b = batch * ci * hi * hi * 2
        out_b = batch * co * ho * ho * 2
        total += 3 * in_b + 5 * out_b
    total += 25.6e6 * 4 * 4                      # fp32 params+momentum r/w
    return total


def _resnet50(batch=128, img=224, steps=40):
    """Batch 128 won the r03 sweep (64:2546, 128:2716, 192:2474, 256:2594,
    512:2453 imgs/s — BENCH_DETAILS resnet50_batch_sweep). The batch lives
    on device across timing calls: re-feeding host arrays per call costs
    ~5s over the tunnel's ~30MB/s H2D and is a harness artifact, not model
    throughput; streamed-input training is the run_epoch + DevicePrefetcher
    path (tests/test_parallel.py::test_run_epoch_device_prefetch).

    r04 roofline finding: the step is HBM-BOUND, not MXU-bound — the
    device profile shows every hot fusion running at 630-660 GiB/s
    against a measured ~650 GB/s elementwise roof, with conv FLOP
    utilization ~0.1-0.2% on those fusions. MFU is the wrong lens for
    this model; roofline efficiency is reported instead. The step moves
    ~1.4x the ideal-folding traffic floor (BN's two-pass nature and
    saved-activation re-reads account for most of the excess).
    Experiments that did NOT move the needle (all measured on-chip):
    NHWC-internal convs (2787 vs 2708), full channels-last pure-jax
    model (2750), breaking the conv+BN-stats fusion (2606),
    1x1-conv-as-einsum (2036). The r04 op-profile refines the story:
    the 'convolution fusion' category is ~78% of device time because
    XLA already fuses each conv with its BN-stats reduction and the
    apply+relu+add chains into single passes — the bottleneck 1x1
    convs are themselves bandwidth-bound at these shapes (AI ~50
    FLOP/B). r05 CLOSED the question: the fused conv+BN Pallas kernel
    was built (ops/fused_conv.py, numerically exact, fwd+bwd incl.
    stats cotangents) and measured 0.18-0.88x vs XLA at every
    bottleneck shape; the 1x1-as-dot_general rewrite measured 2-4x at
    the chain level but 2200 vs 2708 imgs/s end to end (layout
    transitions). Leaf-event profiling shows every hot category within
    ~15% of its own traffic/MXU floor. XLA's compilation of this model
    is the envelope on this chip; see roofline.note."""
    import jax

    from paddle_tpu.optimizer import functional as fopt
    from paddle_tpu.parallel import SpmdTrainer, init_mesh
    from paddle_tpu.vision.models import resnet50

    BATCH, IMG = batch, img
    mesh = init_mesh(dp=1, devices=[jax.devices()[0]])
    net = resnet50(num_classes=1000)

    ce = _softmax_ce

    tr = SpmdTrainer(net, ce, fopt.momentum(0.1, 0.9), mesh=mesh,
                     compute_dtype="bfloat16")
    rs = np.random.RandomState(0)
    imgs = rs.randn(BATCH, 3, IMG, IMG).astype(np.float32)
    labels = rs.randint(0, 1000, (BATCH,)).astype(np.int64)
    key = jax.random.PRNGKey(0)
    d_imgs, d_labels = tr.shard_batch(imgs, labels)

    def run_n(n):
        t0 = time.perf_counter()
        lf = float(tr.run_steps((d_imgs,), d_labels, n, rng=key))
        dt = time.perf_counter() - t0
        assert lf == lf, "ResNet produced NaN loss"
        return dt

    dt, dt_e2e, slopes = _marginal_step_time(run_n, steps, lo_frac=4)
    v = BATCH / dt
    hbm_bw = _hbm_profile()
    min_bytes = _resnet50_min_traffic(BATCH)
    floor_s = min_bytes / hbm_bw
    # reference class: paddlepaddle-gpu ResNet-50 fp16 ~780 imgs/s/V100
    return {"metric": "resnet50_train_imgs_per_sec_per_chip",
            "value": round(v, 2), "unit": "imgs/s",
            "vs_baseline": round(v / 780.0, 3),
            "e2e_value": round(BATCH / dt_e2e, 2),
            "spread": _spread([BATCH / s for s in slopes]),
            "roofline": {
                "hbm_bw_bytes_per_s": round(hbm_bw),
                "min_traffic_bytes_per_step": round(min_bytes),
                "hbm_floor_imgs_per_sec": round(BATCH / floor_s, 1),
                "frac_of_hbm_floor": round(v / (BATCH / floor_s), 3),
                "note": "step is HBM-bound; floor = ideal-folding "
                        "activation+grad bytes / measured ELEMENTWISE "
                        "HBM bandwidth — r05 established that floor is "
                        "MISCALIBRATED low: matmul/conv read streams "
                        "measure ~925 GB/s effective vs the 669 GB/s "
                        "elementwise roof, so frac_of_hbm_floor < 1 "
                        "does not indicate recoverable headroom. r05 "
                        "leaf-event trace (6-step window): conv "
                        "fusions ~24% (~= their MXU floor), BN stats "
                        "convert_reduce ~32% and BN-bwd "
                        "multiply_subtract ~25% — each within ~15% of "
                        "its own traffic floor for the passes exact "
                        "BN training structurally requires. The r04 "
                        "'unbuilt lever' was BUILT and measured this "
                        "round: the VMEM-persistent fused "
                        "scale+relu+matmul+stats Pallas kernel "
                        "(ops/fused_conv.py) loses 0.18-0.88x to "
                        "XLA's own dot_general fusions at every "
                        "bottleneck shape (fused_kernel_ab below), "
                        "and the 1x1-conv-as-dot_general rewrite wins "
                        "2-4x chain-level but loses end-to-end (2200 "
                        "vs 2708 imgs/s: dot/conv layout transitions) "
                        "— PT_CONV1X1_DOT stays off. Verdict: XLA's "
                        "conv+BN compilation is at the achievable "
                        "envelope on this chip; the honest ceiling is "
                        "the structural BN pass count, not a missing "
                        "kernel.",
                "fused_kernel_ab": {
                    "unit": "ms fwd+bwd, B128",
                    "shapes": {
                        "Ci256_Co64_HW3136": {"fused": 1.93,
                                              "xla": 0.54},
                        "Ci64_Co256_HW3136": {"fused": 1.42,
                                              "xla": 0.31},
                        "Ci512_Co128_HW784": {"fused": 1.06,
                                              "xla": 0.28},
                        "Ci128_Co512_HW784": {"fused": 0.70,
                                              "xla": 0.13},
                        "Ci1024_Co256_HW196": {"fused": 0.64,
                                               "xla": 0.13},
                        "Ci2048_Co512_HW49": {"fused": 1.06,
                                              "xla": 0.93}},
                    "conv1x1_as_dot_e2e_imgs_per_sec": 2200}},
            "method": "two-point marginal over jitted multi-step scans on a "
                      "device-resident batch (fixed remote-dispatch latency "
                      "excluded; e2e_value keeps it included)"}


def _mnist_static(batch=256, steps=4000):
    # steps=4000 (r05, was 2000): LeNet steps are ~0.25ms on-device
    # through the scan path, so short scans leave the marginal
    # noise-dominated (100 steps measured 106% spread; 2000 ~10-20%;
    # 4000 doubles the in-jit signal window against the tunnel's
    # seconds-scale jitter — VERDICT r04 weak #7 dispersion)
    import paddle_tpu.fluid as fluid

    BATCH = batch
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[1, 28, 28], dtype="float32")
        lbl = fluid.layers.data("lbl", shape=[1], dtype="int64")
        c1 = fluid.layers.conv2d(img, 6, 5, padding=2, act="relu")
        p1 = fluid.layers.pool2d(c1, 2, "max", 2)
        c2 = fluid.layers.conv2d(p1, 16, 5, act="relu")
        p2 = fluid.layers.pool2d(c2, 2, "max", 2)
        f1 = fluid.layers.fc(p2, 120, act="relu")
        f2 = fluid.layers.fc(f1, 84, act="relu")
        logits = fluid.layers.fc(f2, 10)
        loss = fluid.layers.reduce_mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor()
    exe.run(startup)
    rs = np.random.RandomState(0)
    img_b = rs.randn(BATCH, 1, 28, 28).astype(np.float32)
    lbl_b = rs.randint(0, 10, (BATCH, 1)).astype(np.int64)
    # device-resident feed: the tunnel's ~30MB/s H2D would otherwise eat
    # ~27ms/step re-sending the same 800KB batch (harness artifact)
    import jax

    feed = {"img": jax.device_put(img_b), "lbl": jax.device_put(lbl_b)}
    exe.run(main, feed, [loss])  # compile 1-step; materialize opt slots

    def run_n(n):
        # Executor.run_n: the whole n-step loop is ONE jitted lax.scan
        # dispatch (r03's pipelined per-step dispatch measured the
        # tunnel's ~8-12ms call latency, not the model — 21.7k imgs/s
        # at 46.6% spread; the scan path measures the Executor itself)
        t0 = time.perf_counter()
        lv = exe.run_n(main, feed, [loss], n=n)[0]
        dt = time.perf_counter() - t0
        assert np.isfinite(lv).all()
        return dt

    dt, _, slopes = _marginal_step_time(run_n, steps)
    v = BATCH / dt
    # anchor: torch-CPU LeNet b256 Adam on this host, 8992.6 imgs/s
    # (single-thread; measured 2026-07-30, see BASELINE.md "Measured
    # anchors") — the CPUPlace-reference class for config 1
    return {"metric": "mnist_lenet_static_imgs_per_sec",
            "value": round(v, 2), "unit": "imgs/s",
            "vs_baseline": round(v / 8992.6, 3),
            "spread": _spread([BATCH / s for s in slopes])}


def _tunnel_profile(sample_bytes=4 << 20):
    """Measure the device link live: fixed per-call latency, H2D and D2H
    bandwidth. Marginal (big - small) cancels the fixed cost out of the
    bandwidth estimates; each point is best-of-3. Returns a dict that
    also feeds the published ceiling math."""
    import jax

    # payloads must be INCOMPRESSIBLE: the link compresses zero-filled
    # buffers and reports 4-5x the bandwidth real embedding/grad data
    # gets (measured live: 67 MB/s on zeros vs ~13 MB/s on random bf16)
    rng = np.random.RandomState(0)

    def h2d_time(nbytes):
        a = rng.randn(max(nbytes // 4, 1)).astype(np.float32)
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            d = jax.device_put(a)
            float(d.ravel()[0])  # only a readback bounds completion here
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    def d2h_time(nbytes):
        # the array must be a fresh on-device computation result each
        # trial: np.asarray of a host-originated device_put (or of an
        # already-read array) returns the cached host copy and measures
        # nothing (seen live: a "4.2 TB/s D2H" artifact)
        base = jax.device_put(
            rng.randn(max(nbytes // 4, 1)).astype(np.float32))
        f = jax.jit(lambda x, c: x + c)
        best = None
        for i in range(3):
            d = f(base, float(i + 1))
            float(d.ravel()[0])  # computation done; only transfer left
            t0 = time.perf_counter()
            np.asarray(d)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best

    t_small = h2d_time(4)
    t_big = h2d_time(sample_bytes)
    h2d_bw = sample_bytes / max(t_big - t_small, 1e-6)
    t_small_d = d2h_time(4)
    t_big_d = d2h_time(sample_bytes)
    d2h_bw = sample_bytes / max(t_big_d - t_small_d, 1e-6)
    return {"fixed_call_latency_s": round(t_small, 4),
            "h2d_bw_bytes_per_s": round(h2d_bw),
            "d2h_bw_bytes_per_s": round(d2h_bw)}


def _ctr_dnn_ps(batch=4096, chunks=8, merge_k=32):
    """Config 5: CTR-DNN, async native PS, K-step merged UNIQUE-row wire.

    The r03 loop paid THREE fixed-latency tunnel calls per step (row H2D,
    step dispatch, grad D2H) — ~0.3s/step of pure latency at 4096 ex per
    step. r04 batches K=16 training steps per transfer via
    MergedSparseStream (reference AsyncCommunicator max_merge_var_num,
    communicator.h:253), and — second iteration — dedups the chunk's ids
    on the pull side (unique_wire): the prefetch thread np.unique's the
    K*B*S ids, pulls only the UNIQUE rows from the pserver, and ships
    (rows[Upad,D] bf16, inv[K,B,S] int32). The jitted chunk gathers
    rows[inv[k]] per step; the grad w.r.t. the unique rows is XLA's
    transposed scatter-add, so the row MERGE runs on the chip and the
    readback is one already-merged [Upad,D] bf16 buffer. The host-side
    np.unique/np.add.at merge plane and the per-occurrence wire bytes
    are gone; the pserver RPCs also carry unique rows only. bf16 on the
    wire halves the link bytes; the pserver table stays fp32. Ceiling
    math from the live-measured link profile is published alongside."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.ps import (Communicator, MergedSparseStream,
                                           PsServer)
    from paddle_tpu.optimizer import functional as fopt

    BATCH, SLOTS, DIM, VOCAB, K = batch, 8, 16, 1_000_000, merge_k
    srv = PsServer(port=0, trainers=1, optimizer="sgd", lr=0.01)
    try:
        comm = Communicator([f"127.0.0.1:{srv.port}"], mode="async",
                            trainer_id=0)
        comm.start()
        # to_device=True: the prefetch thread issues the bf16 device_put
        # for chunk i+1 (rows + inv + labels) while the main loop
        # dispatches chunk i, so H2D never sits on the critical path
        ms = MergedSparseStream(comm, "ctr_emb", DIM, height=VOCAB,
                                wire_dtype="bfloat16", to_device=True,
                                unique_wire=True)
        rs = np.random.RandomState(0)
        params = {"w1": (rs.randn(SLOTS * DIM, 64) * 0.05).astype("f4"),
                  "b1": np.zeros(64, np.float32),
                  "w2": (rs.randn(64, 1) * 0.05).astype("f4"),
                  "b2": np.zeros(1, np.float32)}
        tx = fopt.adam(1e-3)
        opt_state = tx.init(params)

        def loss_fn(p, rows_u, inv_k, y):
            emb = rows_u[inv_k]             # [B,S,D] gather on device
            h = jnp.maximum(
                emb.astype(jnp.float32).reshape(BATCH, -1) @ p["w1"]
                + p["b1"], 0.0)
            pred = h @ p["w2"] + p["b2"]
            return ((pred - y) ** 2).mean()

        @jax.jit
        def run_chunk(p, s, rows_u, inv, ys):
            gacc0 = jnp.zeros(rows_u.shape, jnp.float32)

            def body(carry, inp):
                p, s, gacc = carry
                inv_k, y = inp
                lv, (gp, gr) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1))(p, rows_u, inv_k, y)
                p2, s2 = tx.update(p, gp, s)
                # gr is the [Upad,D] scatter-added row grad for this
                # step — the merge the host used to do with np.add.at
                return (p2, s2, gacc + gr.astype(jnp.float32)), lv
            (p, s, gacc), lvs = jax.lax.scan(body, (p, s, gacc0),
                                             (inv, ys))
            return p, s, gacc.astype(rows_u.dtype), lvs[-1]

        def make_chunk():
            ids = rs.randint(0, VOCAB, (K, BATCH, SLOTS)).astype(np.int64)
            ys = (ids.sum(-1, keepdims=True) % 2).astype(np.float32)
            return ids, ys

        ids0, ys0 = make_chunk()
        ms.prefetch(ids0, aux=ys0)
        upads = []

        def one_chunk():
            nonlocal params, opt_state
            # rows/inv/labels device-resident; uniq stays host-side for
            # the push RPC (it never needs to touch the device)
            rows, inv, uniq, ys_d = ms.get()
            upads.append(rows.shape[0])
            nxt = make_chunk()
            ms.prefetch(nxt[0], aux=nxt[1])    # overlap next pull + H2D
            params, opt_state, gacc, lv = run_chunk(params, opt_state,
                                                    rows, inv, ys_d)
            ms.push_async(uniq, gacc)       # one merged D2H + RPC push
            return lv

        try:
            float(one_chunk())              # compile + warm
            trials = []
            for _ in range(5):              # median-of-5 (r04 verdict
                                            # asked >=5): host-RPC jitter
                t0 = time.perf_counter()
                for _ in range(chunks):
                    lv = one_chunk()
                ms.drain()                  # grads actually at the PS
                float(lv)                   # bound the dispatch queue
                trials.append(BATCH * K * chunks
                              / (time.perf_counter() - t0))
            host_plane = {
                "ps_pull_s_per_chunk": round(
                    ms.pull_seconds / max(ms.chunks, 1), 3),
                "push_plane_s_per_chunk": round(
                    ms.push_seconds / max(ms.chunks, 1), 3),
                "note": "worker-thread seconds. push_plane includes the"
                        " grad readback, which BLOCKS until the scan"
                        " compute finishes (it bounds the dispatch"
                        " queue), plus the unique-row RPC push; the"
                        " host merge plane (np.unique/add.at) moved"
                        " onto the device (unique_wire) and the"
                        " widen/narrow passes moved into the C++"
                        " pserver (bf16 wire opcodes) — the trainer"
                        " host never converts dtypes anymore"}
        finally:
            ms.close()
            comm.stop()  # always reap the async send/recv threads
        v = sorted(trials)[len(trials) // 2]
        upad = int(np.median(upads))
        # ---- published ceiling math (VERDICT r03 weak #1) ----
        # per chunk the tunnel carries: 3 fixed-latency calls (row
        # device_put, scan dispatch, grad readback) + the unique-row
        # payloads. The tunnel's bandwidth varies run to run (measured
        # 5-40 MB/s windows), so the link is profiled directly around
        # the trials. Two ceilings: 'serial' assumes H2D and D2H share
        # one lane; 'duplex' would require them to overlap. r05
        # MEASURED the overlap directly (concurrent device_put +
        # np.asarray from two threads): the tunnel transport
        # SERIALIZES — concurrent wall was ~0.88x of serial, far from
        # max(h2d, d2h) — so 'serial' is the honest ceiling and the
        # duplex number is recorded only as the transport upper bound.
        # The r05 lever was therefore BYTES, not overlap: merge_k=32
        # (from 16) amortizes the fixed calls 2x and deepens the
        # unique-row dedup (1.05M draws -> 650k unique rows), cutting
        # wire bytes per example ~30%. ABSOLUTE ex/s tracks the
        # tunnel's 3x+ window-to-window bandwidth swings (r05 measured
        # 25k-91k ex/s across windows; K-sweep in one fast window:
        # K=16 50.7k / K=32 76.1k / K=64 91.4k) — frac_of_ceiling is
        # the window-invariant health metric and held 0.82-0.90
        # throughout. K=32 keeps staleness in the reference
        # AsyncCommunicator's regime (max_merge_var_num~20).
        link = _tunnel_profile()
        h2d_bytes = (upad * DIM * 2            # unique rows, bf16
                     + K * BATCH * SLOTS * 4   # inv gather map, int32
                     + K * BATCH * 4)          # labels, f32
        d2h_bytes = upad * DIM * 2             # merged row grads, bf16
        t_h2d = h2d_bytes / link["h2d_bw_bytes_per_s"]
        t_d2h = d2h_bytes / link["d2h_bw_bytes_per_s"]
        t_fixed = 3 * link["fixed_call_latency_s"]
        t_ceiling = t_fixed + t_h2d + t_d2h
        t_duplex = t_fixed + max(t_h2d, t_d2h)
        ceiling = BATCH * K / t_ceiling
        ceiling_duplex = BATCH * K / t_duplex
        # anchor: torch-CPU in-process CTR-DNN (same tower/vocab, b512,
        # SparseAdam) on this host: 125337 ex/s — see BASELINE.md. The PS
        # path pays RPC + tunnel H2D/D2H (GB/s on production TPU hosts);
        # the anchor keeps the gap honest rather than hidden.
        return {"metric": "ctr_dnn_async_ps_examples_per_sec",
                "value": round(v, 2), "unit": "ex/s",
                "vs_baseline": round(v / 125337.0, 4),
                "merge_k": K, "wire_dtype": "bfloat16",
                "unique_wire": {"upad_rows": upad,
                                "occurrences": K * BATCH * SLOTS},
                "spread": _spread(trials, kind="trials"),
                "link_profile": link, "host_plane": host_plane,
                "ceiling_ex_per_sec": round(ceiling, 1),
                "frac_of_ceiling": round(v / ceiling, 3),
                "ceiling_duplex_ex_per_sec": round(ceiling_duplex, 1),
                "frac_of_duplex_ceiling": round(v / ceiling_duplex, 3),
                "ceiling_math": (
                    f"chunk = 3 fixed calls x {link['fixed_call_latency_s']}s"
                    f" + {h2d_bytes}B H2D (bf16 unique rows + int32 inv +"
                    f" f32 labels) @ {link['h2d_bw_bytes_per_s']}B/s +"
                    f" {d2h_bytes}B bf16 merged-grad D2H @"
                    f" {link['d2h_bw_bytes_per_s']}B/s =>"
                    f" serial {round(t_ceiling, 3)}s / duplex"
                    f" {round(t_duplex, 3)}s per {BATCH * K} examples")}
    finally:
        srv.stop()


def _long_context_attention(seqs=(1024, 2048, 4096), b=2, h=16, d=64,
                            iters=None):
    """Long-context attention A/B on the real chip: the Pallas flash
    kernel (fwd+bwd, causal) vs XLA's fused reference attention, value
    = flash speedup at the longest sequence. Flash became runnable over
    the tunnel in r04 (typed-literal fixes — see ops/attention.py _z);
    the blockwise kernel's O(S) memory is what makes ring/long-context
    sequence scaling viable at all (SURVEY long-context mandate), so
    the bench guards it stays both correct and fast."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import attention as att

    if not att._flash_usable():
        return {"metric": "long_context_flash_attention",
                "status": "skipped: pallas flash unusable on this "
                          "backend (probe failed)"}
    out = {}
    speedup_last = None
    # per-seq scan lengths sized so the in-jit window is hundreds of ms:
    # per-iteration cost is 0.5-10 ms here, and a marginal slope over a
    # few ms of signal loses to the tunnel's seconds-scale jitter (one
    # captured run had XLA@1024 'slower' than XLA@2048 — pure noise)
    iters_by_seq = {1024: 384, 2048: 128, 4096: 48}
    for S in seqs:
        n_it = iters if iters is not None else iters_by_seq.get(S, 64)
        q = jnp.asarray(
            np.random.RandomState(0).randn(b, h, S, d), jnp.bfloat16)

        def mk(fn):
            # n grad computations inside ONE jitted lax.scan, bounded by
            # a host readback: the tunnel's ~0.1s fixed dispatch latency
            # would otherwise swamp the kernel time (block_until_ready
            # does not actually block over this tunnel — see bench notes)
            def loss(q, k, v):
                return fn(q, k, v).astype(jnp.float32).sum()

            g = jax.grad(loss, (0, 1, 2))

            @functools.partial(jax.jit, static_argnums=3)
            def run_n(q, k, v, n):
                def body(c, _):
                    # perturb in q's OWN dtype: bf16 * f32-carry would
                    # silently promote Q to f32 and benchmark the wrong
                    # precision
                    qp = (q * (1 + c * 1e-9)).astype(q.dtype)
                    gq, gk, gv = g(qp, k, v)
                    return gq.astype(jnp.float32).mean(), None
                c, _ = jax.lax.scan(body, jnp.float32(0.0), None,
                                    length=n)
                return c

            def timed(n):
                t0 = time.perf_counter()
                r = float(run_n(q, q, q, n))
                assert r == r
                return time.perf_counter() - t0

            dt, _, _ = _marginal_step_time(timed, n_it, lo_frac=4)
            return dt

        t_flash = mk(lambda q, k, v: att.flash_attention(
            q, k, v, None, True, None))
        t_ref = mk(lambda q, k, v: att.sdpa_reference(
            q, k, v, None, True, None))
        speedup_last = t_ref / t_flash
        out[f"seq{S}"] = {"flash_ms": round(t_flash * 1e3, 2),
                          "xla_ref_ms": round(t_ref * 1e3, 2),
                          "speedup": round(speedup_last, 3)}
    return {"metric": "long_context_flash_attention",
            "value": round(speedup_last, 3), "unit": "x vs XLA ref",
            "by_seq": out,
            "config": {"batch": b, "heads": h, "head_dim": d,
                       "causal": True, "dtype": "bfloat16"}}


def _fused_optimizer(n_layers=14, hidden=128, steps=30):
    """Fused-vs-per-param optimizer step A/B: Adam + global-norm clip
    over a transformer-shaped bag of many small tensors (the
    dispatch-bound regime the fused step exists for). The per-param path
    launches ~200 jitted calls + N+1 clip reductions per step; the fused
    path is ONE donated XLA dispatch. Runs on CPU (JAX_PLATFORMS=cpu)
    and on the chip alike — the win measured here is host dispatch
    overhead, which is backend-independent."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nn.layer.layers import Parameter

    H = hidden
    shapes = []
    for _ in range(n_layers):  # attn qkv/out + biases, mlp, 2x ln
        shapes += [(H, H)] * 4 + [(H,)] * 4
        shapes += [(H, 4 * H), (4 * H,), (4 * H, H), (H,)]
        shapes += [(H,), (H,)]

    def run_path(fused):
        rs = np.random.RandomState(0)
        params = [Parameter((rs.randn(*s) * 0.02).astype("f4"),
                            name=f"p{i}") for i, s in enumerate(shapes)]
        grads = [Tensor(jnp.asarray(rs.randn(*s).astype("f4")))
                 for s in shapes]
        opt = paddle.optimizer.Adam(
            1e-3, parameters=params,
            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        if not fused:
            opt._use_fused = False
        for p, g in zip(params, grads):
            p.grad = g

        def run_n(n):
            t0 = time.perf_counter()
            for _ in range(n):
                opt.step()
            jax.block_until_ready([p._data for p in params])
            return time.perf_counter() - t0

        run_n(2)  # compile + slot init
        dt, _, slopes = _marginal_step_time(run_n, steps)
        return 1.0 / dt, slopes

    fused_sps, fused_slopes = run_path(True)
    pp_sps, _ = run_path(False)
    return {"metric": "fused_optimizer_step",
            "n_params": len(shapes),
            "rule": "adam + ClipGradByGlobalNorm",
            "fused_steps_per_s": round(fused_sps, 1),
            "per_param_steps_per_s": round(pp_sps, 1),
            "value": round(fused_sps / pp_sps, 2),
            "unit": "x_vs_per_param",
            "spread": _spread([1.0 / s for s in fused_slopes])}


def _cold_start(d_model=32, nhead=2, layers=2, vocab=17, num_slots=4,
                max_len=32, buckets=(2, 4, 8)):
    """Cold-vs-warm engine start A/B: time-to-ready of a ServingEngine
    precompile with an EMPTY persistent AOT cache (every serving
    program traces + compiles) against a restarted engine precompiling
    from the POPULATED cache (every program deserializes — zero
    compiles). The warm side's first request is served under an armed
    retrace sentinel + tracer session: the bench ASSERTS zero compile
    spans before the first token (the PR 11 warm-start guarantee) and
    that warm ready time is strictly faster than cold. Host-side
    compile/deserialize work — backend-independent shape of the win."""
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.profiler import trace as T
    from paddle_tpu.serving import Request, Scheduler, ServingEngine

    def mk_engine():
        paddle.seed(0)
        layer = TransformerDecoderLayer(d_model, nhead, 2 * d_model,
                                        dropout=0.0)
        dec = TransformerDecoder(layer, layers)
        dec.eval()
        return ServingEngine(dec, nn.Embedding(vocab, d_model),
                             nn.Linear(d_model, vocab),
                             num_slots=num_slots, max_len=max_len)

    def serve_one(eng):
        sched = Scheduler(max_queue=8)
        rs = np.random.RandomState(1)
        prompt = rs.randint(2, vocab, (3,)).astype(np.int32)
        prompt[0] = 0
        r = Request(prompt, rs.randn(4, d_model).astype("f4"),
                    max_new_tokens=6, eos_id=1)
        sched.submit(r)
        eng.serve_until_idle(sched, max_iterations=200)
        assert r.result(timeout=10).ok
        return list(r.tokens)

    cache_dir = tempfile.mkdtemp(prefix="pt_aot_bench_")
    try:
        # ---- cold start: empty cache, every program compiles ----
        eng_cold = mk_engine()
        rep_cold = eng_cold.precompile(
            (4, d_model), dtype="float32", prompt_buckets=buckets,
            cache=cache_dir)
        toks_cold = serve_one(eng_cold)
        ttft_cold = eng_cold.metrics.first_ttft_s
        assert rep_cold["compiled"] == rep_cold["programs"], rep_cold

        # ---- warm restart: same pool config, populated cache ----
        eng_warm = mk_engine()
        tr = T.start_session()
        try:
            with T.retrace_sentinel(eng_warm):
                rep_warm = eng_warm.precompile(
                    (4, d_model), dtype="float32",
                    prompt_buckets=buckets, cache=cache_dir)
                toks_warm = serve_one(eng_warm)
        finally:
            T.end_session()
        ttft_warm = eng_warm.metrics.first_ttft_s
        # the PR 11 guarantees, asserted in-bench
        assert rep_warm["warm"] == 1 and rep_warm["compiled"] == 0, \
            rep_warm
        assert tr.counters.get("compiles", 0) == 0, dict(tr.counters)
        assert sum(eng_warm.trace_counts.values()) == 0, \
            dict(eng_warm.trace_counts)
        assert toks_warm == toks_cold, (toks_warm, toks_cold)
        cold_s = rep_cold["time_to_ready_s"]
        warm_s = rep_warm["time_to_ready_s"]
        assert warm_s < cold_s, (warm_s, cold_s)
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {"metric": "cold_start_time_to_ready",
            "programs": rep_cold["programs"],
            "cold_ready_s": round(cold_s, 3),
            "warm_ready_s": round(warm_s, 3),
            "cold_first_ttft_ms": round(ttft_cold * 1e3, 2),
            "warm_first_ttft_ms": round(ttft_warm * 1e3, 2),
            "warm_zero_compiles": True,
            "value": round(cold_s / warm_s, 2),
            "unit": "x_faster_ready_warm_vs_cold"}


def _decode_throughput(points=((4, 64), (16, 64), (4, 128)),
                       d_model=128, nhead=4, ffn=256, n_layers=2,
                       vocab=512, mem_len=8, prompt_len=8):
    """Fused static-cache decode vs the eager concat-cache loop,
    tokens/s at several (batch, max_new_tokens) points. The eager side
    is the reference's cache regime — T.concat grows K/V every token,
    so every step reallocates and re-dispatches; the fused side runs
    prefill once plus ONE jitted lax.scan with StaticKVCache as carry
    (text/generation.py). Greedy outputs are asserted token-identical
    between the two paths, so the A/B can't silently diverge."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.text.generation import (DecodeEngine, bucket_size,
                                            generate_eager)

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)
    eng = DecodeEngine(dec, embed, proj)
    rs = np.random.RandomState(0)
    by_point = {}
    speedup_last = None
    for batch, max_new in points:
        memory = jnp.asarray(rs.randn(batch, mem_len, d_model)
                             .astype("f4"))
        prompt = np.full((batch, prompt_len), 0, np.int32)
        prompt[:, 1:] = rs.randint(2, vocab,
                                   (batch, prompt_len - 1))
        prompt = jnp.asarray(prompt)

        def run_fused():
            t0 = time.perf_counter()
            toks, lens = eng.generate(memory, prompt, bos_id=0,
                                      eos_id=1,
                                      max_new_tokens=max_new)
            jax.block_until_ready(0)  # generate returns host arrays
            return time.perf_counter() - t0, toks

        run_fused()                         # compile
        fused_samples = []
        toks_f = None
        for _ in range(5):
            dt, toks_f = run_fused()
            fused_samples.append(batch * max_new / dt)

        def run_eager():
            t0 = time.perf_counter()
            toks, _ = generate_eager(
                dec, embed, proj, memory, prompt, bos_id=0, eos_id=1,
                max_new_tokens=max_new,
                pad_prompt_to=bucket_size(prompt_len))
            return time.perf_counter() - t0, toks

        run_eager()                         # warm per-shape retraces
        dt_e, toks_e = run_eager()
        if not np.array_equal(np.asarray(toks_f), np.asarray(toks_e)):
            raise AssertionError(
                "fused static-cache greedy diverged from the eager "
                "concat-cache reference")
        fused_samples.sort()
        fused_tps = fused_samples[len(fused_samples) // 2]
        eager_tps = batch * max_new / dt_e
        speedup_last = fused_tps / eager_tps
        by_point[f"b{batch}_n{max_new}"] = {
            "fused_tok_per_s": round(fused_tps, 1),
            "eager_tok_per_s": round(eager_tps, 1),
            "speedup": round(speedup_last, 2),
            "spread": _spread(fused_samples, kind="trials")}
    spec = _spec_decode_ab(dec, embed, proj, d_model=d_model,
                           vocab=vocab)
    return {"metric": "decode_throughput",
            "value": round(speedup_last, 2),
            "unit": "x vs eager concat-cache loop",
            "by_point": by_point,
            "speculative": spec,
            "config": {"layers": n_layers, "d_model": d_model,
                       "nhead": nhead, "vocab": vocab,
                       "prompt_len": prompt_len, "greedy": True,
                       "parity_checked": True}}


def _spec_decode_ab(dec, embed, proj, *, d_model, vocab, spec_k=8,
                    ngram=2, max_new=96, pairs=5):
    """Speculative-decoding A/B over the serving engine's per-step
    dispatch path — the regime the feature targets: at batch 1-8 each
    decode step is one host dispatch whose overhead dominates this
    box's tiny-model compute, and draft-verify turns one-dispatch-per-
    token into two dispatches per accepted run. Workload: a
    repetitive-suffix prompt (the self-speculation sweet spot —
    templated text / copy-through); tokens asserted BIT-IDENTICAL to
    the non-spec engine per request. PAIRED per-pair ratio, alternating
    order inside pairs, median-of-pairs (the repo's 1-core noise
    discipline). The fused whole-scan DecodeEngine spec path is
    measured by tools/op_bench.py spec_decode_* rows instead (on this
    compute-bound CPU the k-wide verify pays ~k, so the fused-scan win
    only appears on bandwidth-bound hardware)."""
    import jax  # noqa: F401  (engine imports lazily)

    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.scheduler import Request, Scheduler

    def mk_engine(with_spec, slots):
        kw = dict(spec_k=spec_k, spec_ngram=ngram) if with_spec else {}
        return ServingEngine(dec, embed, proj, num_slots=slots,
                             max_len=160, **kw)

    def serve(eng, prompt, n_req):
        mem = np.random.RandomState(9).randn(8, d_model).astype("f4")
        sched = Scheduler(max_queue=32)
        reqs = [Request(prompt.copy(), mem, max_new_tokens=max_new,
                        eos_id=1) for _ in range(n_req)]
        for r in reqs:
            sched.submit(r)
        t0 = time.perf_counter()
        eng.serve_until_idle(sched)
        dt = time.perf_counter() - t0
        toks = [list(r.result(timeout=5).tokens) for r in reqs]
        return sum(len(t) for t in toks) / dt, toks

    # copy-through prompt: seed the model with a repeated pattern, then
    # use its OWN greedy continuation as the served prompt — the
    # continuation keeps following the attractor it is already on, the
    # canonical self-speculation-friendly (templated/copy-through)
    # regime
    rs = np.random.RandomState(3)
    seed_prompt = np.zeros((8,), np.int32)
    seed_prompt[1:] = np.tile(rs.randint(2, vocab, (4,)), 2)[:7]
    seeder = mk_engine(False, 1)
    _, seed_toks = serve(seeder, seed_prompt, 1)
    prompt0 = np.zeros((33,), np.int32)
    prompt0[1:] = seed_toks[0][:32]

    out = {}
    for batch in (1, 8):
        base = mk_engine(False, batch)
        spec = mk_engine(True, batch)
        serve(base, prompt0, batch)           # compile both paths
        serve(spec, prompt0, batch)
        ratios, spec_tps_s, base_tps_s = [], [], []
        toks_b = toks_s = None
        for i in range(pairs):
            order = (base, spec) if i % 2 == 0 else (spec, base)
            a_tps, a_toks = serve(order[0], prompt0, batch)
            b_tps, b_toks = serve(order[1], prompt0, batch)
            if order[0] is base:
                bt, st_, btk, stk = a_tps, b_tps, a_toks, b_toks
            else:
                bt, st_, btk, stk = b_tps, a_tps, b_toks, a_toks
            ratios.append(st_ / bt)
            spec_tps_s.append(st_)
            base_tps_s.append(bt)
            toks_b, toks_s = btk, stk
        if toks_b != toks_s:
            raise AssertionError(
                "speculative serving decode diverged from the "
                "non-spec engine (greedy acceptance must be "
                "bit-exact)")
        ratios.sort()
        med = ratios[len(ratios) // 2]
        snap = spec.metrics.snapshot()["speculation"]
        out[f"b{batch}"] = {
            "spec_tok_per_s": round(sorted(spec_tps_s)[pairs // 2], 1),
            "base_tok_per_s": round(sorted(base_tps_s)[pairs // 2], 1),
            "speedup": round(med, 2),
            "acceptance_rate": snap["acceptance_rate"],
            "draft_step_ms_p50": snap["draft_step_ms"].get("p50"),
            "verify_step_ms_p50": snap["verify_step_ms"].get("p50"),
            "spread": _spread(ratios, kind="pairs")}
    if out["b1"]["speedup"] < 1.5:
        raise AssertionError(
            f"speculative decode A/B below the 1.5x floor at batch 1: "
            f"{out['b1']}")
    return dict(out, spec_k=spec_k, ngram=ngram, max_new=max_new,
                bit_match_asserted=True,
                workload="copy-through prompt (the model's own "
                         "continuation), serving slot pool")


def _model_param_bytes(*nets):
    """Analytic weight bytes: every parameter's size x itemsize,
    straight off the Layer API (independent of the engines' ledger)."""
    total = 0
    for net in nets:
        for p in net.parameters():
            total += int(np.prod(p.shape)) * 4
    return total


def _expected_dense_pool_bytes(dec, *, num_slots, max_len, mem_len,
                               d_model, itemsize=4):
    """Closed-form dense slot-pool footprint: per layer the [S, H, L,
    D] K+V incremental caches + int32 write index and the [S, Hc, M,
    Dc] cross-attention K+V, plus the pooled tok/bias/memory rows."""
    S, L, M = num_slots, max_len, mem_len
    total = 4 * S + 4 * S * L + itemsize * S * M * d_model
    for layer in dec.layers:
        h, dh = layer.self_attn.num_heads, layer.self_attn.head_dim
        total += 2 * S * h * L * dh * itemsize + 4 * S
        hc, dc = layer.cross_attn.num_heads, layer.cross_attn.head_dim
        total += 2 * S * hc * M * dc * itemsize
    return total


def _expected_paged_pool_bytes(dec, *, num_slots, max_len, mem_len,
                               d_model, page_size, num_pages,
                               kv_dtype=None, itemsize=4):
    """Closed-form paged pool footprint: per layer the [P+1, H, page,
    D] K+V page arrays in the storage dtype (+ per-(page, head) f32
    scales when quantized) and the [S, Hc, M, Dc] cross K+V, plus
    tok/bias/memory rows and the int32 page table."""
    from paddle_tpu.serving.paging import resolve_kv_dtype

    import jax.numpy as jnp

    S, L, M = num_slots, max_len, mem_len
    max_pages = L // page_size
    total = 4 * S + 4 * S * L + itemsize * S * M * d_model
    total += S * max_pages * 4                    # device page table
    storage, quantized = resolve_kv_dtype(kv_dtype, jnp.float32)
    st_item = jnp.dtype(storage).itemsize
    for layer in dec.layers:
        h, dh = layer.self_attn.num_heads, layer.self_attn.head_dim
        total += 2 * (num_pages + 1) * h * page_size * dh * st_item
        if quantized:
            total += 2 * (num_pages + 1) * h * 4  # [P+1, H, 1, 1] f32
        hc, dc = layer.cross_attn.num_heads, layer.cross_attn.head_dim
        total += 2 * S * hc * M * dc * itemsize
    return total


def _serving_throughput(n_requests=48, num_slots=8, d_model=128,
                        nhead=4, ffn=256, n_layers=2, vocab=512,
                        mem_len=8, max_new=12, prompt_max=8):
    """Continuous batching vs static-batch drain under Poisson
    arrivals. A side: the serving runtime — requests join the 8-slot
    ServingEngine the iteration a slot frees, so TTFT is one prefill
    away and short requests never wait on long co-residents. B side:
    the legacy regime — arrivals accumulate while DecodeEngine.generate
    drains the current batch; everyone in a batch waits for the whole
    batch (tokens only surface at the end), and nobody joins mid-run.
    Same model, same arrival schedule, same per-request work; reports
    tok/s plus p50/p99 TTFT for both."""
    import jax.numpy as jnp

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import Request, Scheduler, ServingEngine
    from paddle_tpu.text.generation import DecodeEngine

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)
    rs = np.random.RandomState(0)

    def mk_workload():
        """(prompt [P], lengths, memory) per request; prompts ragged,
        right-padded copies for the static side (fixed P0=prompt_max
        so the static engine compiles one prompt bucket)."""
        work = []
        for _ in range(n_requests):
            P = int(rs.randint(1, prompt_max + 1))
            prompt = rs.randint(2, vocab, (prompt_max,)).astype("i4")
            prompt[0] = 0
            mem = rs.randn(mem_len, d_model).astype("f4")
            work.append((prompt, P, mem))
        return work

    work = mk_workload()
    max_len = bucket_sz = 1 << (prompt_max - 1).bit_length()
    max_len = bucket_sz + max_new

    # ---- A: continuous batching (synchronous drive, real clock) ----
    eng = ServingEngine(dec, embed, proj, num_slots=num_slots,
                        max_len=max_len)
    sched = Scheduler(max_queue=n_requests + 8)
    # warm every join bucket + the step before timing
    for P in sorted({1 << (max(p, 1) - 1).bit_length()
                     for _, p, _ in work}):
        r = Request(work[0][0][:P].copy(), work[0][2],
                    max_new_tokens=1, eos_id=1)
        sched.submit(r)
        eng.serve_until_idle(sched, max_iterations=50)

    gap = 0.004   # mean Poisson inter-arrival (s): ~arrival/iteration
    gaps = rs.exponential(gap, n_requests)
    reqs = []
    with _maybe_trace("serving_throughput") as trace_art:
        t0 = time.perf_counter()
        next_arrival = t0
        i = 0
        while i < len(work) or sched.depth() > 0 or eng.occupancy() > 0:
            now = time.perf_counter()
            while i < len(work) and now >= next_arrival:
                prompt, P, mem = work[i]
                reqs.append(sched.submit(Request(
                    prompt[:P].copy(), mem, max_new_tokens=max_new,
                    eos_id=1)))
                next_arrival += gaps[i]
                i += 1
            eng.run_iteration(sched)
        cont_wall = time.perf_counter() - t0
    cont_ttft = np.asarray([r.result().ttft_s for r in reqs])
    cont_tokens = sum(len(r.result().tokens) for r in reqs)

    # ---- B: static-batch drain on DecodeEngine.generate ----
    deng = DecodeEngine(dec, embed, proj)
    for b in (1, 2, 4, 8):   # warm the batch buckets the drain hits
        mems = jnp.asarray(np.stack([work[0][2]] * b))
        pr = jnp.asarray(np.stack([work[0][0]] * b))
        ln = jnp.asarray(np.full((b,), work[0][1], "i4"))
        deng.generate(mems, pr, ln, bos_id=0, eos_id=1,
                      max_new_tokens=max_new)
    t0 = time.perf_counter()
    next_arrival = t0
    arrived = []          # (arrival_time, index)
    stat_ttft = []
    stat_tokens = 0
    i = 0
    while i < len(work) or arrived:
        now = time.perf_counter()
        while i < len(work) and now >= next_arrival:
            arrived.append((next_arrival, i))
            next_arrival += gaps[i]
            i += 1
        if not arrived:
            time.sleep(max(0.0, next_arrival - now))
            continue
        batch = arrived[:num_slots]   # same concurrency as the pool
        arrived = arrived[num_slots:]
        mems = jnp.asarray(np.stack([work[j][2] for _, j in batch]))
        pr = jnp.asarray(np.stack([work[j][0] for _, j in batch]))
        ln = jnp.asarray(np.asarray([work[j][1] for _, j in batch],
                                    "i4"))
        toks, lens = deng.generate(mems, pr, ln, bos_id=0, eos_id=1,
                                   max_new_tokens=max_new)
        t_done = time.perf_counter()
        stat_tokens += int(np.asarray(lens).sum())
        stat_ttft.extend(t_done - t_arr for t_arr, _ in batch)
    stat_wall = time.perf_counter() - t0
    stat_ttft = np.asarray(stat_ttft)

    # ---- armed-overhead A/B on the decode step ----
    # A steady pool (4 resident requests, no joins, no finishes) runs
    # pure decode iterations in alternating groups with the FULL
    # observability stack OFF and ON — tracer session + cost-accounting
    # session (MFU/goodput gauges) + HBM-ledger budget; identical
    # compiled work either way, so the medians isolate the
    # instrumentation's own cost. Asserted: armed stays within 2% of
    # disarmed — the accounting layer must be deployable always-on.
    from paddle_tpu.profiler import costs as C
    from paddle_tpu.profiler import trace as T

    ov_eng = ServingEngine(dec, embed, proj, num_slots=4, max_len=516,
                           hbm_budget_bytes=1 << 30)
    ov_sched = Scheduler(max_queue=8)
    for k in range(4):
        ov_sched.submit(Request(work[k][0][:2].copy(), work[k][2],
                                max_new_tokens=512, eos_id=None))
    for _ in range(8):                 # join all four + warm the step
        ov_eng.run_iteration(ov_sched)
    ov_book = C.CostBook()  # reused across armed steps: steady state

    def _one(tracer):
        if tracer is not None:
            T.start_session(tracer=tracer)
            C.start_accounting(book=ov_book)
        s0 = time.perf_counter()
        ov_eng.run_iteration(ov_sched)
        dt = time.perf_counter() - s0
        if tracer is not None:
            C.end_accounting()
            T.end_session()
        return dt

    # PAIRED per-step measurement: each (off, on) pair runs back to
    # back — the median of per-pair differences cancels the 1-core
    # box's drift (cpu freq, gc, scheduler) that group medians cannot
    tr = T.Tracer(capacity=1 << 15)
    off_s, diff_s = [], []
    for k in range(200):
        if k % 2 == 0:                 # alternate order inside pairs
            off = _one(None)
            on = _one(tr)
        else:
            on = _one(tr)
            off = _one(None)
        off_s.append(off)
        diff_s.append(on - off)
    off_ms = float(np.median(off_s)) * 1e3
    diff_ms = float(np.median(diff_s)) * 1e3
    on_ms = off_ms + diff_ms
    overhead_pct = diff_ms / off_ms * 100.0
    assert overhead_pct < 2.0, \
        f"armed accounting+tracing overhead {overhead_pct:.2f}% >= " \
        f"2% (on {on_ms:.3f}ms vs off {off_ms:.3f}ms per decode step)"
    ov_eng.abort_active("shutdown")

    # ---- HBM-ledger exactness (dense pool) ----
    # the snapshot's memory section must equal the ANALYTIC pool+weight
    # footprint, computed here from the model/pool config alone
    snap_mem = eng.metrics.snapshot()["memory"]
    exp = _expected_dense_pool_bytes(
        dec, num_slots=num_slots, max_len=max_len, mem_len=mem_len,
        d_model=d_model, itemsize=4)
    exp_w = _model_param_bytes(dec, embed, proj)
    assert snap_mem["total_bytes"] == exp + exp_w, \
        f"ledger {snap_mem['total_bytes']} != analytic " \
        f"{exp + exp_w} (pool {exp} + weights {exp_w})"

    def pct(a, q):
        return round(float(np.percentile(a, q)) * 1e3, 1)

    cont_tps = cont_tokens / cont_wall
    stat_tps = stat_tokens / stat_wall
    return {"metric": "serving_throughput",
            "value": round(float(np.percentile(stat_ttft, 50) /
                                 np.percentile(cont_ttft, 50)), 2),
            "unit": "x lower p50 TTFT vs static-batch drain",
            "continuous": {"tok_per_s": round(cont_tps, 1),
                           "ttft_p50_ms": pct(cont_ttft, 50),
                           "ttft_p99_ms": pct(cont_ttft, 99),
                           "wall_s": round(cont_wall, 2)},
            "static_drain": {"tok_per_s": round(stat_tps, 1),
                             "ttft_p50_ms": pct(stat_ttft, 50),
                             "ttft_p99_ms": pct(stat_ttft, 99),
                             "wall_s": round(stat_wall, 2)},
            "trace_overhead": {
                "armed": "tracer+costs+ledger",
                "off_step_ms": round(off_ms, 3),
                "on_step_ms": round(on_ms, 3),
                "overhead_pct": round(overhead_pct, 2),
                "asserted_lt_pct": 2.0,
                "steps_per_side": len(off_s)},
            "memory_ledger": {
                "total_bytes": snap_mem["total_bytes"],
                "analytic_bytes": exp + exp_w,
                "exact_match": True},
            **({} if trace_art[0] is None
               else {"trace_artifact": trace_art[0]}),
            "config": {"n_requests": n_requests, "slots": num_slots,
                       "layers": n_layers, "d_model": d_model,
                       "max_new_tokens": max_new,
                       "poisson_mean_gap_ms": 4,
                       "prompt_len": f"1..{prompt_max} ragged"}}


def _serving_paged(n_requests=40, d_model=64, nhead=2, ffn=128,
                   n_layers=2, vocab=128, mem_len=4, max_len=128,
                   page_size=16, dense_slots=4, prompt_max=8,
                   shared_frac=0.8):
    """Paged vs dense KV pool at EQUAL cache-memory budget. Both pools
    get the same HBM: the dense side spends it on `dense_slots` rows of
    worst-case `max_len` positions; the paged side turns the identical
    byte budget into `dense_slots * max_len / page_size` pages and lets
    slots map only what they actually use — with ragged requests (mean
    live length <= max_len / 4) that sustains several times the
    concurrency, and 80% of requests sharing one system prompt ride the
    prefix cache with zero re-prefill. Everything is submitted up
    front, so p50 TTFT measures queue wait at each pool's real
    capacity. fp32 pages: the bench ASSERTS the paged tokens bit-match
    the dense pool per request, the paged pool's peak concurrency is
    >= 2x the dense pool's, and the allocator free list returns to its
    initial state after the drain (no page leaks)."""
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import Request, Scheduler, ServingEngine

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)
    rs = np.random.RandomState(0)

    # equal-HBM sizing: positions_budget = dense_slots * max_len
    num_pages = dense_slots * max_len // page_size
    paged_slots = 4 * dense_slots     # capacity now bounded by pages,
    #                                   not rows — give it headroom
    sys_prompt = rs.randint(2, vocab, (prompt_max,)).astype("i4")
    sys_prompt[0] = 0
    sys_mem = rs.randn(mem_len, d_model).astype("f4")
    work = []
    for i in range(n_requests):
        n_new = int(rs.randint(4, 25))     # ragged: mean live length
        #                                    ~22 <= max_len / 4
        if rs.rand() < shared_frac:
            work.append((sys_prompt.copy(), sys_mem, n_new))
        else:
            P = int(rs.randint(1, prompt_max + 1))
            p = rs.randint(2, vocab, (P,)).astype("i4")
            p[0] = 0
            work.append((p, rs.randn(mem_len, d_model).astype("f4"),
                         n_new))

    def drive(eng):
        sched = Scheduler(max_queue=n_requests + 8)
        # warm every join bucket + the step outside the timed window
        for P in sorted({1 << (max(p.shape[0], 1) - 1).bit_length()
                         for p, _, _ in work}):
            r = Request(work[0][0][:P].copy(), work[0][1],
                        max_new_tokens=1, eos_id=1)
            sched.submit(r)
            eng.serve_until_idle(sched, max_iterations=200)
        if hasattr(eng, "flush_prefix_cache"):
            eng.flush_prefix_cache()   # warmup must not seed the cache
        peak = [0]

        class _Occ:
            def on_iteration(self, stats):
                peak[0] = max(peak[0], stats["occupancy"])
        eng._cbs.append(_Occ())
        reqs = []
        t0 = time.perf_counter()
        for p, m, n_new in work:
            reqs.append(sched.submit(Request(
                p.copy(), m, max_new_tokens=n_new, eos_id=1)))
        eng.serve_until_idle(sched, max_iterations=20000)
        wall = time.perf_counter() - t0
        res = [r.result() for r in reqs]
        assert all(r.ok for r in res), \
            [r.finish_reason for r in res if not r.ok]
        ttft = np.asarray([r.ttft_s for r in res])
        toks = sum(len(r.tokens) for r in res)
        return res, ttft, toks, wall, peak[0]

    dense = ServingEngine(dec, embed, proj, num_slots=dense_slots,
                          max_len=max_len, max_joins_per_iter=4)
    d_res, d_ttft, d_toks, d_wall, d_peak = drive(dense)

    paged = ServingEngine(dec, embed, proj, num_slots=paged_slots,
                          max_len=max_len, paged=True,
                          page_size=page_size, num_pages=num_pages,
                          max_joins_per_iter=4)
    with _maybe_trace("serving_paged") as trace_art:
        p_res, p_ttft, p_toks, p_wall, p_peak = drive(paged)

    # fp32 pages: bit-identical tokens to the dense pool, per request
    for a, b in zip(d_res, p_res):
        np.testing.assert_array_equal(a.tokens, b.tokens)
    # acceptance: >= 2x concurrent requests at equal cache memory
    assert p_peak >= 2 * d_peak, (p_peak, d_peak)
    # the shared system prompt rode the prefix cache (zero re-prefill):
    # only the distinct (prompt, memory) combos ever ran a prefill
    pm = paged.metrics
    assert pm.prefix_hits / max(1, pm.prefix_hits + pm.prefix_misses) \
        >= shared_frac - 0.1
    # no page leaks after the drain
    paged.flush_prefix_cache()
    paged._alloc.check()
    assert paged._alloc.pages_free == paged.num_pages
    full = paged.metrics.snapshot()
    snap = full["paging"]
    # HBM-ledger exactness (paged pool): snapshot vs the closed-form
    # page/scale/table footprint + the Layer-API weight bytes
    exp_pool = _expected_paged_pool_bytes(
        dec, num_slots=paged_slots, max_len=paged.max_len,
        mem_len=mem_len, d_model=d_model, page_size=page_size,
        num_pages=num_pages)
    exp_w = _model_param_bytes(dec, embed, proj)
    assert full["memory"]["total_bytes"] == exp_pool + exp_w, \
        (full["memory"], exp_pool, exp_w)

    def pct(a, q):
        return round(float(np.percentile(a, q)) * 1e3, 1)

    return {"metric": "serving_paged",
            "value": round(p_peak / max(1, d_peak), 2),
            "unit": "x peak concurrent requests vs dense pool at "
                    "equal cache memory",
            "bitmatch_dense": True,
            "memory_ledger": {
                "total_bytes": full["memory"]["total_bytes"],
                "analytic_bytes": exp_pool + exp_w,
                "exact_match": True},
            **({} if trace_art[0] is None
               else {"trace_artifact": trace_art[0]}),
            "paged": {"peak_concurrency": p_peak,
                      "ttft_p50_ms": pct(p_ttft, 50),
                      "ttft_p99_ms": pct(p_ttft, 99),
                      "tok_per_s": round(p_toks / p_wall, 1),
                      "prefix_hit_rate": snap["prefix_hit_rate"],
                      "wall_s": round(p_wall, 2)},
            "dense": {"peak_concurrency": d_peak,
                      "ttft_p50_ms": pct(d_ttft, 50),
                      "ttft_p99_ms": pct(d_ttft, 99),
                      "tok_per_s": round(d_toks / d_wall, 1),
                      "wall_s": round(d_wall, 2)},
            "config": {"n_requests": n_requests,
                       "cache_positions_budget": dense_slots * max_len,
                       "dense_slots": dense_slots,
                       "paged_slots": paged_slots,
                       "num_pages": num_pages, "page_size": page_size,
                       "max_len": max_len,
                       "shared_system_prompt_frac": shared_frac,
                       "max_new_tokens": "4..24 ragged (mean ~14)"}}


def _serving_paged_spec(d_model=128, nhead=4, ffn=256, n_layers=2,
                        vocab=512, mem_len=8, max_len=160,
                        page_size=16, spec_k=8, ngram=2, max_new=96,
                        pairs=5):
    """Speculative decoding ON THE PAGED POOL: paged+spec vs
    paged-plain at EQUAL cache memory (identical page pool both
    sides), batch 1 and 8, copy-through workload — the regime where
    draft-verify turns one-dispatch-per-token into two dispatches per
    accepted run while the block table keeps live bytes tracking
    actual tokens. Tokens are asserted BIT-IDENTICAL between the two
    paged engines per request, both pools drain leak-free (allocator
    free list back to initial), and the batch-1 acceptance rate must
    clear a floor (the workload is the self-speculation sweet spot —
    a collapsed acceptance means the paged verify path broke).
    PAIRED per-pair ratio, alternating order inside pairs,
    median-of-pairs (the repo's 1-core noise discipline)."""
    import jax  # noqa: F401  (engine imports lazily)

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.paging import pages_for
    from paddle_tpu.serving.scheduler import Request, Scheduler

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)

    # equal cache memory: BOTH pools get the same page pool, sized so
    # one slot can hold prompt + budget + the spec overhang
    pages_per_slot = pages_for(max_len + spec_k, page_size)

    def mk_engine(with_spec, slots):
        kw = dict(spec_k=spec_k, spec_ngram=ngram) if with_spec else {}
        return ServingEngine(dec, embed, proj, num_slots=slots,
                             max_len=max_len, paged=True,
                             page_size=page_size,
                             num_pages=slots * pages_per_slot, **kw)

    def serve(eng, prompt, n_req):
        mem = np.random.RandomState(9).randn(
            mem_len, d_model).astype("f4")
        sched = Scheduler(max_queue=32)
        reqs = [Request(prompt.copy(), mem, max_new_tokens=max_new,
                        eos_id=1) for _ in range(n_req)]
        for r in reqs:
            sched.submit(r)
        t0 = time.perf_counter()
        eng.serve_until_idle(sched)
        dt = time.perf_counter() - t0
        toks = [list(r.result(timeout=5).tokens) for r in reqs]
        return sum(len(t) for t in toks) / dt, toks

    # copy-through prompt: the model's own greedy continuation (see
    # decode_throughput.speculative) — templated/copy-through regime
    rs = np.random.RandomState(3)
    seed_prompt = np.zeros((8,), np.int32)
    seed_prompt[1:] = np.tile(rs.randint(2, vocab, (4,)), 2)[:7]
    seeder = mk_engine(False, 1)
    _, seed_toks = serve(seeder, seed_prompt, 1)
    prompt0 = np.zeros((33,), np.int32)
    prompt0[1:] = seed_toks[0][:32]

    out = {}
    with _maybe_trace("serving_paged_spec") as trace_art:
        for batch in (1, 8):
            base = mk_engine(False, batch)
            spec = mk_engine(True, batch)
            serve(base, prompt0, batch)       # compile both paths
            serve(spec, prompt0, batch)
            ratios, spec_tps_s, base_tps_s = [], [], []
            toks_b = toks_s = None
            for i in range(pairs):
                order = (base, spec) if i % 2 == 0 else (spec, base)
                a_tps, a_toks = serve(order[0], prompt0, batch)
                b_tps, b_toks = serve(order[1], prompt0, batch)
                if order[0] is base:
                    bt, st_, btk, stk = a_tps, b_tps, a_toks, b_toks
                else:
                    bt, st_, btk, stk = b_tps, a_tps, b_toks, a_toks
                ratios.append(st_ / bt)
                spec_tps_s.append(st_)
                base_tps_s.append(bt)
                toks_b, toks_s = btk, stk
            if toks_b != toks_s:
                raise AssertionError(
                    "paged speculative decode diverged from the "
                    "paged non-spec engine (greedy acceptance must "
                    "be bit-exact)")
            for eng in (base, spec):          # no page leaks
                eng.flush_prefix_cache()
                eng._alloc.check()
                assert eng._alloc.pages_free == eng.num_pages, \
                    (eng._alloc.pages_free, eng.num_pages)
            ratios.sort()
            med = ratios[len(ratios) // 2]
            snap = spec.metrics.snapshot()["speculation"]
            out[f"b{batch}"] = {
                "spec_tok_per_s":
                    round(sorted(spec_tps_s)[pairs // 2], 1),
                "base_tok_per_s":
                    round(sorted(base_tps_s)[pairs // 2], 1),
                "speedup": round(med, 2),
                "acceptance_rate": snap["acceptance_rate"],
                "effective_k": snap["effective_k"],
                "k_shrink_events": snap["k_shrink_events"],
                "draft_step_ms_p50": snap["draft_step_ms"].get("p50"),
                "verify_step_ms_p50":
                    snap["verify_step_ms"].get("p50"),
                "spread": _spread(ratios, kind="pairs")}
    if out["b1"]["speedup"] < 1.3:
        raise AssertionError(
            f"paged speculative A/B below the 1.3x floor at batch 1: "
            f"{out['b1']}")
    if out["b1"]["acceptance_rate"] < 0.25:
        raise AssertionError(
            f"paged spec acceptance collapsed on the copy-through "
            f"workload: {out['b1']}")
    return {"metric": "serving_paged_spec",
            "value": out["b1"]["speedup"],
            "unit": "x tokens/s vs paged non-spec at equal cache "
                    "memory (batch 1)",
            **({} if trace_art[0] is None
               else {"trace_artifact": trace_art[0]}),
            **out,
            "bit_match_asserted": True, "leak_free_asserted": True,
            "config": {"spec_k": spec_k, "ngram": ngram,
                       "max_new": max_new, "page_size": page_size,
                       "pages_per_slot": pages_per_slot,
                       "max_len": max_len,
                       "workload": "copy-through prompt (the model's "
                                   "own continuation), paged slot "
                                   "pool"}}


def _serving_radix(n_requests=28, d_model=128, nhead=2, ffn=256,
                   n_layers=2, vocab=128, mem_len=4, max_len=160,
                   page_size=16, num_slots=8, num_pages=192,
                   pre_len=112, probe_reps=5):
    """Radix vs whole-prompt-only prefix reuse on the SAME paged pool,
    two phases. Phase 1 (batch): a branching-conversation drive —
    every prompt extends one 112-token preamble, forking at page
    depths 32/64/96 (plus a mid-page fork at 40 that exercises COW)
    with a 3-4 token divergent tail, so whole-prompt keying almost
    never hits while the radix trie serves the shared prefix as pages
    and prefills ONLY the tail through the bucketed `pattach` program.
    Asserted: radix tokens bit-match the whole-prompt side per request
    (whose forks all ran COLD full prefills), hit TOKEN ratio >= 0.5,
    no retrace across hit lengths (sentinel armed), leak-free
    allocators. Phase 2 (TTFT probes): SEQUENTIAL paired single-
    request probes per fork depth (max_new_tokens=1, so TTFT is join
    cost with no queue wait, alternating sides per rep) — asserted:
    the deepest shared-preamble depth shows a strict median TTFT win.
    Phase 3 (submit host time): the donated joins return a TRACED
    first token and the engine defers the int() sync past the
    admission loop — a paired probe times the 4-join admission
    iteration with sync_tok0 on vs off and asserts deferral never
    slows the submit path. Since PR 17 every join DONATES the pool
    carry (the splice is in place, no whole-pool copy per join) and
    the default mid_page="round_down" policy serves mid-page forks
    from the page boundary instead of COWing the divergent page —
    the two per-join fixed costs that used to mask the 16x
    prefill-position saving on this dispatch-bound 1-core CPU. The
    batch-phase p50s still ride along unasserted: what remains is
    dispatch count, and the fleet-scale p50 win needs a
    bandwidth-bound chip (same caveat as the serving_paged row)."""
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import (Request, Scheduler, ServingEngine,
                                    retrace_sentinel)

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)
    rs = np.random.RandomState(0)

    base = rs.randint(2, vocab, (pre_len,)).astype("i4")
    base[0] = 0
    sys_mem = rs.randn(mem_len, d_model).astype("f4")
    # forks at page boundaries (32/64/96 = 2/4/6 pages of seed) plus a
    # mid-page fork (40 — under the default round_down policy it seeds
    # from the 32-token boundary with no COW; mid_page="cow" would COW
    # the divergent page); tails of 3-4 tokens keep every partial hit
    # on ONE pattach tail bucket
    forks = [32, 64, 96, 40]
    work = []
    for i in range(n_requests):
        n_new = int(rs.randint(4, 13))
        if i % 7 == 0:                      # occasional exact repeat
            p = np.concatenate([base, [5, 9, 2]]).astype("i4")
        else:
            f = forks[int(rs.randint(len(forks)))]
            t = rs.randint(2, vocab, (int(rs.randint(3, 5)),))
            p = np.concatenate([base[:f], t]).astype("i4")
        work.append((p, n_new))

    def mk_engine():
        return ServingEngine(dec, embed, proj, num_slots=num_slots,
                             max_len=max_len, paged=True,
                             page_size=page_size, num_pages=num_pages,
                             prefix_capacity=8, max_joins_per_iter=4)

    def serve_one(eng, p, max_new=2):
        sched = Scheduler(max_queue=4)
        r = Request(np.asarray(p, np.int32), sys_mem,
                    max_new_tokens=max_new, eos_id=1)
        sched.submit(r)
        eng.serve_until_idle(sched, max_iterations=500)
        res = r.result(timeout=60)
        assert res.ok
        return res

    def warm(eng):
        # compile every program the timed phases will touch — join
        # bucket 128, attach (whole hit), cow (mid-page fork), and the
        # pattach pair for each fork depth — then drop the entries so
        # the batch phase rebuilds the trie from cold
        for p in ([np.concatenate([base, [5, 9, 2]]).astype("i4")] * 2
                  + [np.concatenate([base[:f], [3, 7, 12]]).astype("i4")
                     for f in forks]):
            serve_one(eng, p)
        eng.flush_prefix_cache()
        # warmup consulted the cache too — reset() zeroes every counter
        # (prefix hits included) so the snapshot reflects the timed
        # phases only, while keeping the engine's memory-ledger wiring
        # (TTFT is taken from per-request results, not metrics)
        eng.metrics.reset()

    def drive(eng):
        sched = Scheduler(max_queue=n_requests + 8)
        reqs = []
        t0 = time.perf_counter()
        for p, n_new in work:
            reqs.append(sched.submit(Request(
                p.copy(), sys_mem, max_new_tokens=n_new, eos_id=1)))
        eng.serve_until_idle(sched, max_iterations=20000)
        wall = time.perf_counter() - t0
        res = [r.result() for r in reqs]
        assert all(r.ok for r in res), \
            [r.finish_reason for r in res if not r.ok]
        ttft = np.asarray([r.ttft_s for r in res])
        toks = sum(len(r.tokens) for r in res)
        return res, ttft, toks, wall

    # ---- B side: same pool, whole-prompt reuse only (the flat
    # PrefixCache semantics PR 16 replaced) — forks re-prefill cold
    whole = mk_engine()
    whole._partial_ok = False
    warm(whole)
    w_res, w_ttft, w_toks, w_wall = drive(whole)

    # ---- A side: radix partial reuse, retrace sentinel armed over
    # the timed phases (warmup compiled every bucket pair)
    radix = mk_engine()
    warm(radix)
    with _maybe_trace("serving_radix") as trace_art:
        with retrace_sentinel(radix):
            r_res, r_ttft, r_toks, r_wall = drive(radix)

    # partial-hit generation bit-matches the whole-prompt side, whose
    # forked prompts all ran cold full prefills
    for a, b in zip(w_res, r_res):
        np.testing.assert_array_equal(a.tokens, b.tokens)
    m = radix.metrics
    assert m.prefix_partial_hits >= 3, m.prefix_partial_hits
    snap = m.snapshot()["prefix"]
    assert snap["hit_token_ratio"] >= 0.5, snap
    # the default round_down policy serves mid-page forks from the
    # page boundary: no COW dispatches at all in the batch phase
    assert snap["cow_copies"] == 0, snap

    # ---- phase 2: paired sequential TTFT probes per fork depth.
    # max_new_tokens=1 makes TTFT the join cost itself (no queue
    # wait); fresh tails per rep keep every radix consult a PARTIAL
    # hit; order alternates per rep to cancel drift
    prs = np.random.RandomState(1)
    depth_win = {}
    with retrace_sentinel(radix):
        for f in forks:
            pairs = []
            for rep in range(probe_reps):
                t = prs.randint(2, vocab, (4,))
                p = np.concatenate([base[:f], t]).astype("i4")
                sides = [(whole, "w"), (radix, "r")]
                if rep % 2:
                    sides.reverse()
                got = {}
                for eng, tag in sides:
                    got[tag] = serve_one(eng, p, max_new=1).ttft_s
                pairs.append((got["w"], got["r"]))
            med_w = float(np.median([a for a, _ in pairs]))
            med_r = float(np.median([b for _, b in pairs]))
            depth_win[f] = {
                "whole_ttft_ms": round(med_w * 1e3, 2),
                "radix_ttft_ms": round(med_r * 1e3, 2),
                "win": round(med_w / max(med_r, 1e-9), 3)}
    # the TTFT win, in-bench: at least one page-aligned shared-
    # preamble depth must beat the whole-prompt-only side (the
    # ISSUE-16 acceptance bar). The headline is the best such depth —
    # per-depth medians ride along so the artifact shows the whole
    # curve, including the mid-page COW depth where the extra copy
    # dispatch can eat the win on this dispatch-bound box
    aligned = [f for f in forks if f % page_size == 0]
    best = max(aligned, key=lambda f: depth_win[f]["win"])
    assert depth_win[best]["win"] > 1.0, depth_win
    # round_down killed the mid-page regression row: the 40-token fork
    # seeds from the 32-token boundary with no COW dispatch, so it
    # must at least hold par with the whole-prompt side (the PR-16
    # committed row LOST ~0.7x here under mid_page="cow")
    for f in forks:
        if f % page_size:
            assert depth_win[f]["win"] > 0.9, depth_win

    # ---- phase 3: submit-path host time, deferred vs eager tok0.
    # sync_tok0=True restores the old behavior — block on int(tok0)
    # inside the admission loop, serializing back-to-back joins; the
    # default defers the sync past the loop so the 4 join dispatches
    # pipeline. Paired + alternated like the TTFT probes; deferral
    # must never slow the submit path (the ISSUE-17 satellite check).
    hrs = np.random.RandomState(2)
    host = {True: [], False: []}
    with retrace_sentinel(radix):
        for rep in range(probe_reps * 2):
            order = (True, False) if rep % 2 else (False, True)
            for flag in order:
                radix.sync_tok0 = flag
                sched = Scheduler(max_queue=8)
                for _ in range(4):
                    t = hrs.randint(2, vocab, (4,))
                    sched.submit(Request(
                        np.concatenate([base[:64], t]).astype("i4"),
                        sys_mem, max_new_tokens=1, eos_id=1))
                t0 = time.perf_counter()
                radix.run_iteration(sched)   # the 4-join admission
                host[flag].append(time.perf_counter() - t0)
                radix.serve_until_idle(sched, max_iterations=200)
    radix.sync_tok0 = False
    sync_ms = float(np.median(host[True])) * 1e3
    defer_ms = float(np.median(host[False])) * 1e3
    assert defer_ms <= sync_ms * 1.15, (defer_ms, sync_ms)

    # leak-free after the drain on both pools
    for eng in (whole, radix):
        eng.flush_prefix_cache()
        eng._alloc.check()
        assert eng._alloc.pages_free == eng.num_pages

    def pct(a, q):
        return round(float(np.percentile(a, q)) * 1e3, 1)

    return {"metric": "serving_radix",
            "value": depth_win[best]["win"],
            "unit": f"x lower TTFT at the best shared-preamble depth "
                    f"({best} tokens matched) vs whole-prompt-only "
                    f"reuse, paired sequential probes",
            "bitmatch_whole_prompt_cold": True,
            "leak_free_asserted": True,
            "retrace_sentinel": "armed over batch drive + probes",
            "ttft_by_depth": {str(k): v for k, v in depth_win.items()},
            "submit_host": {
                "sync_tok0_ms": round(sync_ms, 2),
                "deferred_ms": round(defer_ms, 2),
                "win": round(sync_ms / max(defer_ms, 1e-9), 3)},
            **({} if trace_art[0] is None
               else {"trace_artifact": trace_art[0]}),
            "radix": {"ttft_p50_ms": pct(r_ttft, 50),
                      "ttft_p99_ms": pct(r_ttft, 99),
                      "tok_per_s": round(r_toks / r_wall, 1),
                      "hit_token_ratio": snap["hit_token_ratio"],
                      "whole_hits": snap["whole_hits"],
                      "partial_hits": snap["partial_hits"],
                      "misses": snap["misses"],
                      "cow_copies": snap["cow_copies"],
                      "rounded_down":
                          radix._prefix.stats()["rounded_down"],
                      "full_prefills": radix.prefill_count,
                      "wall_s": round(r_wall, 2)},
            "whole_prompt": {"ttft_p50_ms": pct(w_ttft, 50),
                             "ttft_p99_ms": pct(w_ttft, 99),
                             "tok_per_s": round(w_toks / w_wall, 1),
                             "full_prefills": whole.prefill_count,
                             "wall_s": round(w_wall, 2)},
            "config": {"n_requests": n_requests, "pre_len": pre_len,
                       "fork_depths": forks, "probe_reps": probe_reps,
                       "page_size": page_size, "num_slots": num_slots,
                       "num_pages": num_pages, "max_len": max_len,
                       "prefix_capacity": 8,
                       "max_new_tokens": "4..12 ragged (batch), "
                                         "1 (probes)"}}


def _serving_slo(n_batch=8, n_inter=10, d_model=64, nhead=2, ffn=128,
                 n_layers=2, vocab=64, mem_len=4, max_len=160,
                 page_size=8, num_slots=4, num_pages=224,
                 batch_len=64, batch_new=48, inter_new=6,
                 prefill_chunk=8, gap_reps=3):
    """Traffic shaping vs FIFO on the SAME paged pool at EQUAL offered
    load, three phases. Phase 1 (TTFT under mixed traffic): a bimodal
    open-loop drive — 8 long batch prompts (64 tokens) land at t=0,
    10 short interactive requests arrive Poisson-spaced through the
    busy window (arrival times calibrated to the measured FIFO wall so
    the pool is congested on both sides). Both twins run IDENTICAL
    `prefill_chunk=8` engines — the only variable is the scheduler:
    the FIFO twin admits in arrival order, the shaped side runs
    `ShapingScheduler` (interactive rank 0, batch preemptible), so
    interactive work jumps the queue and preempts batch slots to the
    prefix cache. Asserted: every request's tokens bit-match across
    the two sides (preempt/resume and chunking are invisible in
    output), interactive p99 TTFT wins by >= 1.5x, the shaped wall
    stays within 1.6x of FIFO (scheduling overhead — preemption
    replay plus WFQ bookkeeping — must not eat the equal offered
    load), resumes == preemptions >= 1 with prefill_count <= requests
    (a resume rides the trie attach, never a re-prefill), leak-free
    pools, retrace sentinel armed. Phase 2 (fairness): one hog tenant
    floods 10 requests ahead of a light tenant's 4 on a 2-slot pool;
    at a half-drain token horizon the Jain index over per-tenant
    delivered tokens must IMPROVE under WFQ vs FIFO (arrival order
    starves the light tenant; equal-weight WFQ alternates). Phase 3
    (step-gap bound): co-resident decoders see one long prompt join
    mid-stream — chunked prefill must keep decode-step inter-arrival
    p99 within 6x of a no-join baseline (median of 3 reps; the
    whole-prompt join's gap rides along unasserted for the curve)."""
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import (Request, Scheduler, ServingEngine,
                                    ShapingScheduler, retrace_sentinel)

    def mk_stack(seed=11):
        import paddle_tpu as paddle

        paddle.seed(seed)
        np.random.seed(seed)
        layer = TransformerDecoderLayer(d_model, nhead, ffn,
                                        dropout=0.0)
        dec = TransformerDecoder(layer, n_layers)
        dec.eval()
        return dec, nn.Embedding(vocab, d_model), nn.Linear(d_model,
                                                            vocab)

    def mk_engine(chunk, slots=num_slots):
        dec, embed, proj = mk_stack()
        return ServingEngine(dec, embed, proj, num_slots=slots,
                             max_len=max_len, paged=True,
                             page_size=page_size, num_pages=num_pages,
                             prefix_capacity=32, prefill_chunk=chunk)

    rs = np.random.RandomState(3)

    def mk_prompt(P):
        p = rs.randint(2, vocab, (P,)).astype(np.int32)
        p[0] = 0
        mem = np.random.RandomState(
            int(p.sum()) * 131 + P).randn(mem_len,
                                          d_model).astype("f4")
        return p, mem

    batch_specs = [mk_prompt(batch_len) + (batch_new,)
                   for _ in range(n_batch)]
    inter_specs = [mk_prompt(int(rs.randint(2, 8))) + (inter_new,)
                   for _ in range(n_inter)]

    def mk_reqs(slo=False):
        b = [Request(p.copy(), m, max_new_tokens=n, eos_id=1,
                     **({"slo": "batch"} if slo else {}))
             for p, m, n in batch_specs]
        i = [Request(p.copy(), m, max_new_tokens=n, eos_id=1,
                     **({"slo": "interactive"} if slo else {}))
             for p, m, n in inter_specs]
        return b, i

    resume_len = mk_prompt(batch_len + 8)   # a preempted batch slot's
    # prompt+generated length lands past batch_len: serving this pair
    # compiles the attach/chunk buckets a mid-drive resume rides

    def warm(eng):
        """Compile every program the timed drive touches (join bucket
        8, the pcjoin chunk family or the whole-prompt bucket, decode,
        and the whole-hit attach a resume rides), then reset counters
        and drop the trie so the timed phase starts cold. Returns the
        busy wall — only meaningful on a SECOND call, once every
        program is compiled (the calibration window)."""
        sched = Scheduler(max_queue=64)
        b, i = mk_reqs()
        reqs = b + i
        for p, m in (batch_specs[0][:2], resume_len[:2],
                     resume_len[:2]):     # repeats: whole-hit attach
            reqs.append(Request(p.copy(), m, max_new_tokens=2,
                                eos_id=1))
        for r in reqs:
            sched.submit(r)
        t0 = time.perf_counter()
        eng.serve_until_idle(sched, max_iterations=5000)
        wall = time.perf_counter() - t0
        assert all(r.result(timeout=5).ok for r in reqs)
        eng.flush_prefix_cache()
        eng.metrics.reset()
        return wall

    def timed_drive(eng, sched, schedule):
        """Open-loop: submit each request at its wall-clock arrival
        time while the engine iterates; returns the busy wall."""
        idx = 0
        t0 = time.perf_counter()
        while True:
            now = time.perf_counter() - t0
            while idx < len(schedule) and schedule[idx][0] <= now:
                sched.submit(schedule[idx][1])
                idx += 1
            if sched.depth() == 0 and eng.occupancy() == 0:
                if idx >= len(schedule):
                    break
                time.sleep(max(0.0, min(
                    0.002,
                    schedule[idx][0] - (time.perf_counter() - t0))))
                continue
            eng.run_iteration(sched)
        return time.perf_counter() - t0

    # ---- phase 1: bimodal mixed traffic, shaped vs FIFO twin ----
    # the twins run IDENTICAL chunked engines: per-chunk dispatch on a
    # 1-core CPU costs as much as a decode step, so an unchunked FIFO
    # baseline would fold that fixed cost into the scheduler
    # comparison — phase 3 quantifies chunking itself against a
    # no-join baseline instead
    fifo = mk_engine(prefill_chunk)
    shaped = mk_engine(prefill_chunk)
    warm(shaped)
    warm(fifo)              # first pass compiles
    cal_wall = warm(fifo)   # the congestion window both sides share
    ars = np.random.RandomState(7)
    gaps = np.cumsum(ars.exponential(1.0, n_inter))
    arrive = 0.05 * cal_wall + 0.55 * cal_wall * gaps / gaps[-1]

    def schedule_for(slo):
        b, i = mk_reqs(slo=slo)
        sched = [(0.0, r) for r in b] + list(zip(arrive, i))
        return b, i, sorted(sched, key=lambda e: e[0])

    out = {}
    with _maybe_trace("serving_slo") as trace_art:
        fb, fi, fsched = schedule_for(slo=False)
        f_wall = timed_drive(fifo, Scheduler(max_queue=64), fsched)
        sb, si, ssched = schedule_for(slo=True)
        pc0 = shaped.prefill_count   # engine-lifetime counter: the
        # warm passes' prefills stay in it, only the delta is ours
        with retrace_sentinel(shaped):
            s_wall = timed_drive(
                shaped, ShapingScheduler(max_queue=64,
                                         max_preemptions=1,
                                         metrics=shaped.metrics),
                ssched)
    f_res = [r.result(timeout=5) for r in fb + fi]
    s_res = [r.result(timeout=5) for r in sb + si]
    assert all(r.ok for r in f_res) and all(r.ok for r in s_res)
    # preempt/resume + chunking are invisible in output: every request
    # bit-matches its FIFO twin
    for a, b in zip(f_res, s_res):
        np.testing.assert_array_equal(a.tokens, b.tokens)
    m = shaped.metrics
    assert m.preemptions >= 1, m.preemptions
    assert m.resumes == m.preemptions, (m.resumes, m.preemptions)
    assert m.chunked_prefills >= n_batch, m.chunked_prefills
    # a resume rides the whole-hit trie attach: joins = requests +
    # resumes, yet real prefill programs never exceed the request
    # count (re-prefilling a preempted slot would push it past)
    n_requests = n_batch + n_inter
    prefills = shaped.prefill_count - pc0
    assert prefills <= n_requests, (prefills, n_requests)
    assert m.joins >= n_requests + m.resumes, (m.joins, m.resumes)
    fi_ttft = np.asarray([r.ttft_s for r in f_res[n_batch:]])
    si_ttft = np.asarray([r.ttft_s for r in s_res[n_batch:]])
    f_p99 = float(np.percentile(fi_ttft, 99))
    s_p99 = float(np.percentile(si_ttft, 99))
    ttft_win = f_p99 / max(s_p99, 1e-9)
    assert ttft_win >= 1.5, (f_p99, s_p99)
    # equal offered load on identical engines: the scheduler's own
    # overhead (preemption replay + WFQ bookkeeping) must not blow up
    # the busy wall
    assert s_wall <= f_wall * 1.6, (s_wall, f_wall)
    for eng in (fifo, shaped):
        eng.flush_prefix_cache()
        eng._alloc.check()
        assert eng._alloc.pages_free == eng.num_pages

    # ---- phase 2: WFQ fairness at a half-drain horizon ----
    def jain(xs):
        xs = np.asarray(xs, np.float64)
        return float(xs.sum() ** 2
                     / (len(xs) * (xs ** 2).sum() + 1e-12))

    def fairness_side(shaped_side):
        from paddle_tpu.serving import AdapterPool

        import paddle_tpu as paddle

        paddle.seed(11)
        np.random.seed(11)
        layer = TransformerDecoderLayer(d_model, nhead, ffn,
                                        dropout=0.0)
        dec = TransformerDecoder(layer, n_layers)
        dec.eval()
        embed = nn.Embedding(vocab, d_model)
        proj = nn.Linear(d_model, vocab)
        apool = AdapterPool(dec, capacity=3, rank=4)
        apool.register_random("hog", seed=201, scale=0.05)
        apool.register_random("light", seed=202, scale=0.05)
        eng = ServingEngine(dec, embed, proj, num_slots=2,
                            max_len=64, adapters=apool)
        frs = np.random.RandomState(9)
        reqs = []
        for tenant, n in (("hog", 10), ("light", 4)):
            for _ in range(n):
                P = int(frs.randint(3, 7))
                p = frs.randint(2, vocab, (P,)).astype(np.int32)
                p[0] = 0
                mem = np.random.RandomState(
                    int(p.sum()) * 131 + P).randn(
                        mem_len, d_model).astype("f4")
                reqs.append((tenant, Request(
                    p, mem, max_new_tokens=16, eos_id=1,
                    adapter=tenant)))
        sched = (ShapingScheduler(max_queue=32) if shaped_side
                 else Scheduler(max_queue=32))
        for _, r in reqs:      # the hog's flood submits FIRST
            sched.submit(r)
        total = sum(r.max_new_tokens for _, r in reqs)

        def delivered():
            return sum(len(r.tokens) for _, r in reqs)

        it = 0
        while delivered() < total // 2 and it < 2000:
            eng.run_iteration(sched)
            it += 1
        by_tenant = {"hog": 0, "light": 0}
        for tenant, r in reqs:
            by_tenant[tenant] += len(r.tokens)
        j = jain([by_tenant["hog"], by_tenant["light"]])
        eng.serve_until_idle(sched, max_iterations=5000)
        assert all(r.result(timeout=5).ok for _, r in reqs)
        return j, by_tenant

    j_fifo, t_fifo = fairness_side(shaped_side=False)
    j_wfq, t_wfq = fairness_side(shaped_side=True)
    assert j_wfq > j_fifo, (j_wfq, j_fifo)

    # ---- phase 3: chunked prefill bounds the decode-step gap ----
    def gap_run(chunk, with_long):
        eng = mk_engine(chunk)
        warm(eng)
        sched = Scheduler(max_queue=16)
        decs = [Request(p.copy(), m, max_new_tokens=40, eos_id=1)
                for p, m, _ in inter_specs[:3]]
        for r in decs:
            sched.submit(r)
        for _ in range(3):
            eng.run_iteration(sched)
        reqs = list(decs)
        if with_long:
            p, m, _ = batch_specs[0]
            reqs.append(Request(p.copy(), m, max_new_tokens=1,
                                eos_id=1))
            sched.submit(reqs[-1])
        eng.serve_until_idle(sched, max_iterations=2000)
        assert all(r.result(timeout=5).ok for r in reqs)
        # the gauge is recorded on every engine but only the sharded
        # snapshot renders a "sharding" section — read the reservoir
        return eng.metrics.step_gap_s.summary(scale=1e3)["p99"]

    base_p99 = float(np.median(
        [gap_run(prefill_chunk, False) for _ in range(gap_reps)]))
    chunk_p99 = float(np.median(
        [gap_run(prefill_chunk, True) for _ in range(gap_reps)]))
    whole_p99 = float(np.median(
        [gap_run(None, True) for _ in range(gap_reps)]))
    assert chunk_p99 <= base_p99 * 6.0, (chunk_p99, base_p99)

    def pct(a, q):
        return round(float(np.percentile(a, q)) * 1e3, 1)

    snap = m.snapshot()["slo"]
    out.update({
        "metric": "serving_slo",
        "value": round(ttft_win, 2),
        "unit": "x lower interactive p99 TTFT vs the FIFO twin at "
                "equal offered load (bimodal open-loop drive)",
        "bitmatch_fifo_twin": True,
        "leak_free_asserted": True,
        "retrace_sentinel": "armed over the shaped timed drive",
        "interactive_ttft": {
            "fifo_p50_ms": pct(fi_ttft, 50),
            "fifo_p99_ms": pct(fi_ttft, 99),
            "shaped_p50_ms": pct(si_ttft, 50),
            "shaped_p99_ms": pct(si_ttft, 99)},
        "walls": {"fifo_s": round(f_wall, 2),
                  "shaped_s": round(s_wall, 2)},
        "shaping": {"preemptions": snap["preemptions"],
                    "resumes": snap["resumes"],
                    "replay_tokens": snap["replay_tokens"],
                    "chunked_prefills": snap["chunked_prefills"],
                    "chunks": snap["chunks"],
                    "full_prefills": prefills,
                    "ttft_attainment": snap["ttft_attainment"]},
        "fairness": {"jain_fifo": round(j_fifo, 3),
                     "jain_wfq": round(j_wfq, 3),
                     "tokens_fifo": t_fifo, "tokens_wfq": t_wfq},
        "step_gap_p99_ms": {
            "no_join_baseline": round(base_p99, 2),
            "chunked_join": round(chunk_p99, 2),
            "whole_prompt_join": round(whole_p99, 2),
            "chunked_vs_baseline": round(
                chunk_p99 / max(base_p99, 1e-9), 2)},
        **({} if trace_art[0] is None
           else {"trace_artifact": trace_art[0]}),
        "config": {"n_batch": n_batch, "n_inter": n_inter,
                   "batch_len": batch_len, "batch_new": batch_new,
                   "inter_new": inter_new,
                   "prefill_chunk": prefill_chunk,
                   "page_size": page_size, "num_slots": num_slots,
                   "num_pages": num_pages, "gap_reps": gap_reps}})
    return out


def _serving_multitenant(n_tenants=4, d_model=64, nhead=2, ffn=128,
                         n_layers=2, vocab=64, mem_len=4, rank=8,
                         reqs_per_tenant=4, max_new=24,
                         shared_slots=16, per_tenant_slots=2, pairs=3):
    """Multi-tenant serving A/B at EQUAL HBM budget: one shared pool
    serving N tenants' mixed traffic through batched LoRA adapters
    over an int8 base, vs the naive deployment — one fp32 engine PER
    TENANT (adapter deltas merged into its weights) serving its own
    requests serially. The budget is the naive side's ledger total
    (N weight copies + N small pools); the shared side must FIT UNDER
    it (asserted via memory_ledger) while batching every tenant into
    one decode dispatch — the aggregate tokens/s ratio is the
    headline, asserted >= 2x. The int8 base must also come in >= 1.9x
    under the fp32 weight ledger (asserted exactly from the ledgers).
    Correctness is asserted in-bench: every shared-pool request's
    tokens must equal its tenant's merged-weight engine output
    token-for-token. PAIRED per-pair ratio, alternating order,
    median-of-pairs (the repo's 1-core noise discipline)."""
    import jax  # noqa: F401  (engine imports lazily)

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import AdapterPool, ServingEngine
    from paddle_tpu.serving.scheduler import Request, Scheduler

    def mk_stack(seed):
        # reset BOTH rngs: initializers draw from paddle's key
        # stream, so same-seed reconstruction (the A/B's identical
        # base weights) needs it reset alongside numpy
        import paddle_tpu as paddle

        paddle.seed(seed)
        np.random.seed(seed)
        layer = TransformerDecoderLayer(d_model, nhead, ffn,
                                        dropout=0.0)
        dec = TransformerDecoder(layer, n_layers)
        dec.eval()
        return dec, nn.Embedding(vocab, d_model), nn.Linear(d_model,
                                                            vocab)

    tenants = [f"tenant{i}" for i in range(n_tenants)]

    # ---- B side: one fp32 merged-weight engine per tenant ----
    # every tenant engine clones the SAME base stack construction
    # (same seed -> identical weights) and merges its adapter in
    naive = {}
    pool_ref = None
    for ti, name in enumerate(tenants):
        dec, embed, proj = mk_stack(11)
        pool = AdapterPool(dec, capacity=n_tenants + 1, rank=rank)
        for tj, nm in enumerate(tenants):
            pool.register_random(nm, seed=100 + tj, scale=0.05)
        if pool_ref is None:
            pool_ref = pool
        for i, w in pool.merged_weights(name):
            pool.targets[i].weight._data = w
        naive[name] = ServingEngine(dec, embed, proj,
                                    num_slots=per_tenant_slots,
                                    max_len=64)
    # ---- A side: ONE shared pool, int8 base + adapter banks ----
    dec, embed, proj = mk_stack(11)
    apool = AdapterPool(dec, capacity=n_tenants + 1, rank=rank)
    for tj, nm in enumerate(tenants):
        apool.register_random(nm, seed=100 + tj, scale=0.05)
    shared = ServingEngine(dec, embed, proj, num_slots=shared_slots,
                           max_len=64, adapters=apool, quantize="int8")
    # the CORRECTNESS twin: the same shared pool at fp32 — the
    # factored adapter path must be token-identical to the merged
    # weights; the int8 perf side is only tolerance-bounded (weight
    # rounding can flip an argmax on a tiny bench model)
    dec32, embed32, proj32 = mk_stack(11)
    apool32 = AdapterPool(dec32, capacity=n_tenants + 1, rank=rank)
    for tj, nm in enumerate(tenants):
        apool32.register_random(nm, seed=100 + tj, scale=0.05)
    shared32 = ServingEngine(dec32, embed32, proj32,
                             num_slots=shared_slots, max_len=64,
                             adapters=apool32)

    rs = np.random.RandomState(5)
    prompts = []
    for name in tenants:
        for _ in range(reqs_per_tenant):
            P = int(rs.randint(2, 7))
            p = rs.randint(2, vocab, (P,)).astype(np.int32)
            p[0] = 0
            mem = np.random.RandomState(
                int(p.sum()) * 131 + P).randn(mem_len,
                                              d_model).astype("f4")
            prompts.append((name, p, mem))

    def serve_shared(eng=None):
        eng = eng if eng is not None else shared
        sched = Scheduler(max_queue=64)
        reqs = []
        for name, p, mem in prompts:
            r = Request(p.copy(), mem, max_new_tokens=max_new,
                        eos_id=1, adapter=name)
            reqs.append((name, r))
            sched.submit(r)
        t0 = time.perf_counter()
        eng.serve_until_idle(sched)
        dt = time.perf_counter() - t0
        toks = [(name, list(r.result(timeout=5).tokens))
                for name, r in reqs]
        return sum(len(t) for _, t in toks) / dt, toks

    def serve_naive():
        total = 0
        t0 = time.perf_counter()
        toks = []
        for name in tenants:
            sched = Scheduler(max_queue=64)
            reqs = []
            for nm, p, mem in prompts:
                if nm != name:
                    continue
                r = Request(p.copy(), mem, max_new_tokens=max_new,
                            eos_id=1)
                reqs.append(r)
                sched.submit(r)
            naive[name].serve_until_idle(sched)
            for r in reqs:
                t = list(r.result(timeout=5).tokens)
                toks.append((name, t))
                total += len(t)
        dt = time.perf_counter() - t0
        return total / dt, toks

    out = {}
    with _maybe_trace("serving_multitenant") as trace_art:
        serve_shared()            # compile both sides
        serve_naive()
        ratios, a_s, b_s = [], [], []
        toks_a = toks_b = None
        for i in range(pairs):
            order = (serve_naive, serve_shared) if i % 2 == 0 \
                else (serve_shared, serve_naive)
            x_tps, x_toks = order[0]()
            y_tps, y_toks = order[1]()
            if order[0] is serve_naive:
                bt, at = x_tps, y_tps
                toks_b, toks_a = x_toks, y_toks
            else:
                bt, at = y_tps, x_tps
                toks_b, toks_a = y_toks, x_toks
            ratios.append(at / bt)
            a_s.append(at)
            b_s.append(bt)
    # correctness: the fp32 shared pool's factored adapter decode ==
    # merged-weight solo engines, token for token, per request — the
    # acceptance bit-match (sorted into the same multiset order)
    _, toks_32 = serve_shared(shared32)
    if sorted(map(repr, toks_32)) != sorted(map(repr, toks_b)):
        raise AssertionError(
            "fp32 shared multi-tenant pool diverged from the "
            "per-tenant merged-weight engines")
    # int8 perf side: tolerance-bounded, not bit-exact — record the
    # token agreement vs the fp32 twin and require it not collapse
    agree = tot = 0
    for (na, ta), (n3, t3) in zip(sorted(toks_a), sorted(toks_32)):
        for x, y in zip(ta, t3):
            tot += 1
            agree += int(x == y)
    int8_agreement = agree / max(1, tot)
    if int8_agreement < 0.8:
        raise AssertionError(
            f"int8 shared pool token agreement collapsed vs fp32: "
            f"{int8_agreement:.3f}")
    # equal-HBM budget: the shared side fits under the naive total
    shared_mem = shared.metrics.snapshot()["memory"]
    naive_mems = [e.metrics.snapshot()["memory"]
                  for e in naive.values()]
    budget = sum(m["total_bytes"] for m in naive_mems)
    if shared_mem["total_bytes"] > budget:
        raise AssertionError(
            f"shared pool ({shared_mem['total_bytes']}b) exceeds the "
            f"naive deployment's HBM budget ({budget}b)")
    # int8 base >= 1.9x under ONE fp32 copy (weights only, exact)
    w_ratio = naive_mems[0]["weights_bytes"] / \
        shared_mem["weights_bytes"]
    if w_ratio < 1.9:
        raise AssertionError(
            f"int8 weight ledger only {w_ratio:.2f}x under fp32 "
            f"(>= 1.9x required)")
    ratios.sort()
    med = ratios[len(ratios) // 2]
    if med < 2.0:
        raise AssertionError(
            f"shared multi-tenant pool below the 2x aggregate "
            f"tokens/s floor vs serial per-tenant pools: {med:.2f}x "
            f"(shared {sorted(a_s)}, naive {sorted(b_s)})")
    snap = shared.metrics.snapshot()
    out = {
        "metric": "serving_multitenant",
        "value": round(med, 2),
        "unit": "x aggregate tokens/s vs serial per-tenant fp32 "
                "pools at equal HBM budget",
        **({} if trace_art[0] is None
           else {"trace_artifact": trace_art[0]}),
        "shared_tok_per_s": round(sorted(a_s)[pairs // 2], 1),
        "naive_tok_per_s": round(sorted(b_s)[pairs // 2], 1),
        "weights_int8_bytes": shared_mem["weights_bytes"],
        "weights_f32_bytes": naive_mems[0]["weights_bytes"],
        "int8_weight_shrink": round(w_ratio, 2),
        "adapter_bytes": shared_mem["adapter_bytes"],
        "shared_total_bytes": shared_mem["total_bytes"],
        "naive_total_bytes": budget,
        "adapter_hit_rate": snap["tenancy"]["adapter_hit_rate"],
        "fairness": snap["tenancy"]["fairness"],
        "bit_match_asserted": "fp32 shared pool == merged-weight "
                              "per-tenant engines",
        "int8_token_agreement": round(int8_agreement, 3),
        "spread": _spread(ratios, kind="pairs"),
        "config": {"n_tenants": n_tenants, "rank": rank,
                   "shared_slots": shared_slots,
                   "per_tenant_slots": per_tenant_slots,
                   "reqs_per_tenant": reqs_per_tenant,
                   "max_new": max_new, "d_model": d_model,
                   "vocab": vocab,
                   "workload": "mixed-tenant random prompts, one "
                               "shared int8+LoRA pool vs N resident "
                               "fp32 merged-weight pools served "
                               "serially"}}
    return out


def _serving_sharded(n_requests=24, d_model=64, nhead=2, ffn=128,
                     n_layers=2, vocab=128, mem_len=4, max_new=10,
                     prompt_max=8, dense_slots=4, long_prompt=40,
                     resident_new=48):
    """Mesh-sharded serving A/B on the 8-virtual-device CPU mesh.

    Part 1 — pool scaling at EQUAL per-device cache memory: the
    single-chip engine gets `dense_slots` rows on one CPU device; the
    sharded engine (dp=2 x fsdp=2 x tp=2) gets `2 * dense_slots` rows
    sharded over dp — the same rows-per-device budget, with weights
    laid out fsdp x tp in the bit-exact "gathered" layout. The bench
    ASSERTS every request's tokens bit-match between the two pools.
    CPU caveat: one host core executes all 8 virtual devices, so
    tokens/s measures structure and overhead, not the memory-capacity
    scaling a real pod sees (the pool and the weights it can hold DO
    scale with the mesh; wall clock here cannot).

    Part 2 — prefill/decode disaggregation under concurrent long-prompt
    joins: 4 resident requests decode while a long-prompt (bucket-64)
    request joins EVERY iteration. Inline prefill blocks each iteration
    on the full prompt prefill; the disaggregated engine dispatches it
    to the prefill dp slice and splices asynchronously. The metric is
    the decode-step inter-arrival p50 (`step_gap_ms`) the residents
    see between their tokens; the bench asserts the disaggregated
    path's p50 is LOWER."""
    import os

    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    try:
        cpus = jax.devices("cpu")
    except Exception:
        cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if len(cpus) < 8:
        return {"metric": "serving_sharded",
                "status": "skipped: needs 8 virtual cpu devices (run "
                          "with XLA_FLAGS=--xla_force_host_platform_"
                          "device_count=8 before jax initializes)"}

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.parallel import init_mesh
    from paddle_tpu.serving import (Request, Scheduler, ServingEngine,
                                    ShardedServingEngine)

    layer = TransformerDecoderLayer(d_model, nhead, ffn, dropout=0.0)
    dec = TransformerDecoder(layer, n_layers)
    dec.eval()
    embed = nn.Embedding(vocab, d_model)
    proj = nn.Linear(d_model, vocab)
    rs = np.random.RandomState(0)
    mesh = init_mesh(dp=2, fsdp=2, tp=2, devices=cpus[:8])

    max_len = (1 << (prompt_max - 1).bit_length()) + max_new
    work = []
    for _ in range(n_requests):
        P = int(rs.randint(1, prompt_max + 1))
        p = rs.randint(2, vocab, (P,)).astype("i4")
        p[0] = 0
        work.append((p, rs.randn(mem_len, d_model).astype("f4")))

    def drive(eng):
        sched = Scheduler(max_queue=n_requests + 8)
        reqs = []
        t0 = time.perf_counter()
        for p, m in work:
            reqs.append(sched.submit(Request(
                p.copy(), m, max_new_tokens=max_new, eos_id=1)))
        eng.serve_until_idle(sched, max_iterations=20000)
        wall = time.perf_counter() - t0
        res = [r.result() for r in reqs]
        assert all(r.ok for r in res)
        ttft = np.asarray([r.ttft_s for r in res])
        toks = sum(len(r.tokens) for r in res)
        return res, ttft, toks, wall

    with jax.default_device(cpus[0]):   # pin the 1-chip side to ONE
        #                                 cpu device for a fair A/B
        dense = ServingEngine(dec, embed, proj, num_slots=dense_slots,
                              max_len=max_len, max_joins_per_iter=4)
        d_res, d_ttft, d_toks, d_wall = drive(dense)

    shard = ShardedServingEngine(dec, embed, proj, mesh=mesh,
                                 num_slots=2 * dense_slots,
                                 max_len=max_len, max_joins_per_iter=4)
    with _maybe_trace("serving_sharded") as trace_art:
        s_res, s_ttft, s_toks, s_wall = drive(shard)

    # the acceptance bit-match: fp32 gathered layout, per request
    for a, b in zip(d_res, s_res):
        np.testing.assert_array_equal(a.tokens, b.tokens)

    # ---- part 2: disaggregated vs inline prefill ----
    LONG_MAXLEN = (1 << (long_prompt - 1).bit_length()) + 16
    lp = rs.randint(2, vocab, (long_prompt,)).astype("i4")
    lp[0] = 0
    lmem = rs.randn(mem_len, d_model).astype("f4")
    residents = []
    for _ in range(4):
        p = rs.randint(2, vocab, (2,)).astype("i4")
        p[0] = 0
        residents.append((p, rs.randn(mem_len, d_model).astype("f4")))

    def measure(policy):
        eng = ShardedServingEngine(dec, embed, proj, mesh=mesh,
                                   num_slots=6, max_len=LONG_MAXLEN,
                                   prefill=policy,
                                   max_joins_per_iter=1)
        sched = Scheduler(max_queue=512)
        warm = []
        for p, m in [(lp, lmem), residents[0]]:
            r = Request(p.copy(), m, max_new_tokens=1, eos_id=None)
            sched.submit(r)
            warm.append(r)
        eng.serve_until_idle(sched, max_iterations=200)
        res = [Request(p.copy(), m, max_new_tokens=resident_new,
                       eos_id=None) for p, m in residents]
        for r in res:
            sched.submit(r)
        for _ in range(6):              # join the residents
            eng.run_iteration(sched)
        n0 = len(eng.metrics.step_gap_s._buf)
        n_long = 0
        it = 0
        while any(r.state != "DONE" for r in res):
            sched.submit(Request(lp.copy(), lmem, max_new_tokens=2,
                                 eos_id=None))
            n_long += 1
            eng.run_iteration(sched)
            it += 1
            assert it < 1000
        gaps = np.asarray(eng.metrics.step_gap_s._buf[n0:]) * 1e3
        eng.abort_active("shutdown")
        sched.abort_queued("shutdown")
        sh = eng.metrics.snapshot()["sharding"]
        return gaps, n_long, sh

    inline_gaps, inline_longs, _ = measure("inline")
    dis_gaps, dis_longs, dis_sh = measure("disaggregated")
    inline_p50 = float(np.percentile(inline_gaps, 50))
    dis_p50 = float(np.percentile(dis_gaps, 50))
    # the acceptance: disaggregated prefill stops stealing decode-step
    # latency from co-resident requests
    assert dis_p50 < inline_p50, (dis_p50, inline_p50)

    def pct(a, q):
        return round(float(np.percentile(a, q)) * 1e3, 1)

    return {"metric": "serving_sharded",
            "value": round(inline_p50 / dis_p50, 2),
            "unit": "x lower decode-step p50 with disaggregated "
                    "prefill under concurrent long-prompt joins",
            "bitmatch_single_chip": True,
            **({} if trace_art[0] is None
               else {"trace_artifact": trace_art[0]}),
            "pool_scaling": {
                "dense_1dev": {"slots": dense_slots,
                               "tok_per_s": round(d_toks / d_wall, 1),
                               "ttft_p50_ms": pct(d_ttft, 50),
                               "wall_s": round(d_wall, 2)},
                "sharded_8dev": {"slots": 2 * dense_slots,
                                 "mesh": "dp2 x fsdp2 x tp2",
                                 "tok_per_s": round(s_toks / s_wall,
                                                    1),
                                 "ttft_p50_ms": pct(s_ttft, 50),
                                 "wall_s": round(s_wall, 2)},
                "note": "equal rows-per-device; CPU mesh measures "
                        "structure, not bandwidth"},
            "disaggregation": {
                "inline_step_gap_p50_ms": round(inline_p50, 2),
                "disagg_step_gap_p50_ms": round(dis_p50, 2),
                "inline_long_joins": inline_longs,
                "disagg_long_joins": dis_longs,
                "prefill_step_p50_ms":
                    dis_sh["prefill_step_ms"].get("p50"),
                "collective_time_share":
                    dis_sh["collective_time_share"]},
            "config": {"n_requests": n_requests, "d_model": d_model,
                       "layers": n_layers, "max_new_tokens": max_new,
                       "long_prompt_len": long_prompt,
                       "layout": "gathered (bit-exact)"}}


def _multichip_scaling(devices=None, sizes_mb=(4, 64), ar_iters=8,
                       dp_steps=6):
    """Config 4 harness: fleet collective allreduce bandwidth + DP weak
    scaling. Runs whenever >1 device is visible — real chips on a pod
    host, or the 8-virtual-device CPU mesh the test suite pins — so the
    moment multi-chip hardware appears, `python bench.py multichip`
    measures the BASELINE.md north star (fleet allreduce GB/s, >70%
    linear scaling) with no new code. On this 1-chip host the full bench
    records it as skipped; the CPU-mesh test keeps the path honest.

    busbw uses the standard ring-allreduce accounting: each device moves
    2*(N-1)/N of the buffer over the links per allreduce."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    if n < 2:
        return {"metric": "fleet_allreduce_scaling",
                "status": "skipped: single real chip; harness validated "
                          "on the 8-device CPU mesh "
                          "(tests/test_parallel.py) and by "
                          "__graft_entry__.dryrun_multichip(8)"}
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(devs), ("dp",))
    bands = {}
    for mb in sizes_mb:
        elems = (mb << 20) // 4
        per = -(-elems // n)

        @jax.jit
        @functools.partial(shard_map, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp"))
        def reduce_k(x):
            def body(c, _):
                # typed scale (weak python /n breaks the carry type) and
                # pvary (psum output is axis-invariant; the carry came
                # in dp-varying — scan requires matching varying axes)
                r = jax.lax.psum(c, "dp") * jnp.float32(1.0 / n)
                return jax.lax.pvary(r, "dp"), None
            c, _ = jax.lax.scan(body, x, None, length=ar_iters)
            return c

        x = jnp.ones((n * per,), jnp.float32)
        float(reduce_k(x).sum())          # compile + warm
        t0 = time.perf_counter()
        float(reduce_k(x).sum())          # readback bounds completion
        dt = (time.perf_counter() - t0) / ar_iters
        algbw = (elems * 4) / dt
        bands[f"{mb}MB"] = {
            "algbw_GBps": round(algbw / 1e9, 3),
            "busbw_GBps": round(algbw * 2 * (n - 1) / n / 1e9, 3)}

    # DP weak scaling: fixed per-device batch, same jitted step on a
    # 1-device mesh vs the full mesh
    import paddle_tpu.nn as pnn
    from paddle_tpu.optimizer import functional as fopt
    from paddle_tpu.parallel import SpmdTrainer, init_mesh

    def make_trainer(sub):
        m = init_mesh(dp=len(sub), devices=sub)
        net = pnn.Sequential(pnn.Linear(256, 512), pnn.ReLU(),
                             pnn.Linear(512, 10))

        ce = _softmax_ce

        tr = SpmdTrainer(net, ce, fopt.momentum(0.1, 0.9), mesh=m)
        B = 512 * len(sub)
        xs = np.random.RandomState(1).randn(B, 256).astype("f4")
        ys = np.random.RandomState(2).randint(0, 10, (B,)).astype("i8")
        dx, dy = tr.shard_batch(xs, ys)
        # warm the SAME step count: run_steps caches jitted loops per n,
        # so warming n=2 and timing n=dp_steps would time a compile
        float(tr.run_steps((dx,), dy, dp_steps))
        t0 = time.perf_counter()
        float(tr.run_steps((dx,), dy, dp_steps))
        return B * dp_steps / (time.perf_counter() - t0)

    tput1 = make_trainer(devs[:1])
    tputn = make_trainer(devs)
    eff = (tputn / n) / tput1
    return {"metric": "fleet_allreduce_scaling",
            "n_devices": n,
            "allreduce": bands,
            "dp_weak_scaling": {
                "tput_1dev_ex_per_s": round(tput1, 1),
                f"tput_{n}dev_ex_per_s": round(tputn, 1),
                "efficiency": round(eff, 3),
                "target": ">0.70 linear scaling (BASELINE.md)"}}


CONFIG_TIMEOUT_S = 1500

_DETAILS_PATH = None


def _details_path():
    """BENCH_DETAILS.json next to this script, independent of cwd (the
    per-config subprocesses run with cwd = script dir; the parent must
    read the same file)."""
    global _DETAILS_PATH
    if _DETAILS_PATH is None:
        import os

        _DETAILS_PATH = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_DETAILS.json")
    return _DETAILS_PATH


def _read_details():
    try:
        with open(_details_path()) as f:
            return json.load(f)
    except Exception:
        return {}


def main():
    global _TRACE
    argv = list(sys.argv[1:])
    _TRACE = "--trace" in argv
    argv = [a for a in argv if a != "--trace"]
    only = argv[0] if argv else None
    configs = [("mnist", _mnist_static), ("resnet50", _resnet50),
               ("ernie", _ernie), ("ctr_ps", _ctr_dnn_ps),
               ("long_context", _long_context_attention),
               ("ernie_long", _ernie_long),
               ("packed_varlen", _packed_varlen),
               ("fused_optimizer", _fused_optimizer),
               ("decode_throughput", _decode_throughput),
               ("cold_start", _cold_start),
               ("serving_throughput", _serving_throughput),
               ("serving_paged", _serving_paged),
               ("serving_paged_spec", _serving_paged_spec),
               ("serving_radix", _serving_radix),
               ("serving_slo", _serving_slo),
               ("serving_multitenant", _serving_multitenant),
               ("serving_sharded", _serving_sharded),
               ("multichip_scaling", _multichip_scaling)]
    results = {}
    headline = None
    if only is None:
        # full run: one subprocess per config with a hard timeout, so a
        # pathological backend compile (seen live: conv wgrad blowups on
        # the remote toolchain) can stall ONE config, never the bench
        import os
        import subprocess

        for name, _ in configs:
            # clear any stale record first: a child that dies before
            # writing must surface as an error, not last run's number
            stale = _read_details()
            if name in stale:
                stale.pop(name)
                with open(_details_path(), "w") as f:
                    json.dump(stale, f, indent=1)
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), name]
                    + (["--trace"] if _TRACE else []),
                    timeout=CONFIG_TIMEOUT_S,
                    stdout=subprocess.DEVNULL,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
                if proc.returncode != 0:
                    results[name] = {
                        "metric": name,
                        "error": f"subprocess exited {proc.returncode}"}
            except subprocess.TimeoutExpired:
                results[name] = {
                    "metric": name,
                    "error": f"timeout after {CONFIG_TIMEOUT_S}s"}
        merged = _read_details()
        for name, _ in configs:  # subprocesses merged their own entries
            if name in merged and name not in results:
                results[name] = merged[name]
            results.setdefault(name, {"metric": name,
                                      "error": "config produced no record"})
        er = results.get("ernie") or {}
        headline = er if "value" in er else None
    for name, fn in configs:
        if only != name:
            continue
        try:
            r = fn()
        except Exception as e:  # record, keep the headline alive
            r = {"metric": name, "error": f"{type(e).__name__}: {e}"}
        results[name] = r
        print(f"# {name}: {json.dumps(r)}", file=sys.stderr)
        if "value" in r:
            headline = r  # single-config runs headline themselves
    try:
        # MERGE into the record instead of clobbering other entries
        # (other configs' results, sweep records)
        merged = _read_details()
        merged.update(results)
        with open(_details_path(), "w") as f:
            json.dump(merged, f, indent=1)
    except Exception:
        pass
    if headline is None:
        # a config errored (or an unknown name was asked for): report the
        # failure honestly, never a fabricated 0.0 measurement
        headline = results.get("ernie") or {
            "metric": only or "ernie_base_finetune_seq_per_sec_per_chip",
            "error": "config did not produce a measurement"}
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
