"""Headline benchmark: ERNIE-base fine-tune train-step throughput, one chip
(BASELINE.md config 3). Prints ONE JSON line.

vs_baseline is measured against a provisional 300 seq/s target — the
paddlepaddle-gpu BERT/ERNIE-base fp16 fine-tune (seq_len 128) per-V100-chip
class the north star asks us to match (BASELINE.json: no published numbers
exist in the reference repo, so the target is recorded here and refined as
real reference runs land).
"""
from __future__ import annotations

import json
import time

import numpy as np

TARGET_SEQ_PER_SEC = 300.0

BATCH = 32
SEQ_LEN = 128
STEPS = 50


def main():
    import jax

    import paddle_tpu  # noqa: F401
    from paddle_tpu.optimizer import functional as fopt
    from paddle_tpu.parallel import SpmdTrainer, init_mesh
    from paddle_tpu.text import ErnieConfig, ErnieForSequenceClassification

    dev = jax.devices()[0]
    mesh = init_mesh(dp=1, devices=[dev])

    cfg = ErnieConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                      num_heads=12, intermediate_size=3072,
                      max_position=SEQ_LEN + 2, hidden_dropout=0.1,
                      num_classes=2)
    net = ErnieForSequenceClassification(cfg)

    def ce(logits, labels):
        import jax.numpy as jnp

        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.take_along_axis(lp, labels[:, None], -1).mean()

    tr = SpmdTrainer(net, ce, fopt.adamw(5e-5), mesh=mesh,
                     compute_dtype="bfloat16")

    rs = np.random.RandomState(0)
    ids = rs.randint(1, cfg.vocab_size, (BATCH, SEQ_LEN)).astype(np.int64)
    labels = rs.randint(0, 2, (BATCH,)).astype(np.int64)
    key = jax.random.PRNGKey(0)

    # one jitted multi-step loop (lax.scan): a single dispatch covers all
    # STEPS, and the final float() host readback bounds completion — robust
    # against async-dispatch runtimes under-reporting time.
    float(tr.run_steps((ids,), labels, STEPS, rng=key))  # compile + warm

    t0 = time.perf_counter()
    lf = float(tr.run_steps((ids,), labels, STEPS, rng=key))
    dt = time.perf_counter() - t0
    assert lf == lf, "training produced NaN loss"

    seq_per_sec = BATCH * STEPS / dt
    print(json.dumps({
        "metric": "ernie_base_finetune_seq_per_sec_per_chip",
        "value": round(seq_per_sec, 2),
        "unit": "seq/s",
        "vs_baseline": round(seq_per_sec / TARGET_SEQ_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
