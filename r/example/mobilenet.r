#!/usr/bin/env Rscript
# R inference client (reference parity: r/example/mobilenet.r — the
# reference binds R to the predictor through reticulate over the Python
# API, and so does this one; no native R binding exists in either).
#
# Usage: Rscript mobilenet.r <model_dir>
# The model_dir holds a save_inference_model artifact (__model__ +
# __params__). Requires the reticulate R package and a Python with
# paddle_tpu importable.

library(reticulate)

args <- commandArgs(trailingOnly = TRUE)
model_dir <- if (length(args) >= 1) args[[1]] else "mobilenet_model"

np <- import("numpy", convert = FALSE)
inf <- import("paddle_tpu.inference")

set_config <- function() {
    config <- inf$Config(model_dir)
    # config$enable_native_engine()  # uncomment for the C++ engine
    return(config)
}

run_mobilenet <- function() {
    config <- set_config()
    predictor <- inf$create_predictor(config)

    input_names <- predictor$get_input_names()
    input_tensor <- predictor$get_input_handle(input_names[[1]])
    data <- np$random$rand(1L, 3L, 224L, 224L)$astype("float32")
    input_tensor$copy_from_cpu(data)

    predictor$run()

    output_names <- predictor$get_output_names()
    output_tensor <- predictor$get_output_handle(output_names[[1]])
    logits <- py_to_r(output_tensor$copy_to_cpu())
    cat("top-1 class:", which.max(logits) - 1, "\n")
}

run_mobilenet()
