//go:build ignore

// MobileNet Go inference demo (reference parity:
// go/demo/mobilenet.go + r/example/mobilenet.r role): classify a
// 224x224 image with a saved MobileNet artifact through the native
// C++ engine.
//
// Author the artifact with fluid.io.save_inference_model (the
// __model__ + __params__ form the native C++ engine loads — a
// paddle.jit.save export is XLA-engine-only); see
// tests/test_inference.py::test_native_predictor_serves_mobilenet_lite
// for a complete static-graph authoring example of this op family.
//
// Then:
//
//	cd go && CGO_LDFLAGS="-L${REPO}/csrc/build/lib -lptcore \
//	             -Wl,-rpath,${REPO}/csrc/build/lib" \
//	go run ./demo/mobilenet.go -model ../mobilenet_model
package main

import (
	"flag"
	"fmt"
	"log"
	"math/rand"

	"paddle_tpu/go/paddle"
)

func main() {
	model := flag.String("model", "mobilenet_model",
		"saved inference model dir")
	flag.Parse()

	cfg := paddle.NewConfig()
	cfg.SetModel(*model)
	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		log.Fatal(err)
	}
	defer pred.Destroy()

	fmt.Println("inputs:", pred.InputNames())
	fmt.Println("outputs:", pred.OutputNames())

	// synthetic image; a real client decodes + normalizes a JPEG here
	data := make([]float32, 1*3*224*224)
	for i := range data {
		data[i] = rand.Float32()
	}
	if err := pred.SetInput(pred.InputNames()[0],
		paddle.NewTensor([]int64{1, 3, 224, 224}, data)); err != nil {
		log.Fatal(err)
	}

	outs, err := pred.Run()
	if err != nil {
		log.Fatal(err)
	}
	logits := outs[0]
	best, bestV := 0, float32(-1e30)
	for i, v := range logits.Data {
		if v > bestV {
			best, bestV = i, v
		}
	}
	fmt.Printf("top-1 class %d (logit %.4f) of %d\n",
		best, bestV, len(logits.Data))
}
