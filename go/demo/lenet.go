// Demo: run a saved LeNet/MNIST inference model through the Go client
// (reference parity: go/demo/mobilenet.go).
//
// Usage:
//
//	CGO_LDFLAGS="-L../../csrc/build/lib -lptcore" go run lenet.go <model_dir>
package main

import (
	"fmt"
	"log"
	"os"

	"paddle_tpu/go/paddle"
)

func main() {
	if len(os.Args) < 2 {
		fmt.Println("usage: lenet <model_dir>")
		os.Exit(1)
	}
	cfg := paddle.NewConfig()
	cfg.SetModel(os.Args[1])
	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		panic(err)
	}
	defer pred.Destroy()

	in := paddle.NewTensor([]int64{1, 1, 28, 28},
		make([]float32, 28*28))
	if err := pred.SetInput(pred.InputNames()[0], in); err != nil {
		log.Fatal(err)
	}
	outs, err := pred.Run()
	if err != nil {
		panic(err)
	}
	for i, t := range outs {
		fmt.Printf("output %d shape=%v first=%v\n", i, t.Shape,
			t.Data[:min(4, len(t.Data))])
	}
}

func min(a, b int) int {
	if a < b {
		return a
	}
	return b
}
