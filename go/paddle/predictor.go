package paddle

// #cgo LDFLAGS: -lptcore
// #include <stdint.h>
// #include <stdlib.h>
// void* pt_pred_create(const char* model_dir);
// const char* pt_pred_error(void* h);
// int pt_pred_feed_count(void* h);
// const char* pt_pred_feed_name(void* h, int i);
// int pt_pred_fetch_count(void* h);
// const char* pt_pred_fetch_name(void* h, int i);
// void pt_pred_set_input(void* h, const char* name, const int64_t* dims,
//                        int ndim, const float* data);
// void pt_pred_set_input_i64(void* h, const char* name,
//                            const int64_t* dims, int ndim,
//                            const int64_t* data);
// int pt_pred_set_input_lod(void* h, const char* name,
//                           const int64_t* offsets, int n);
// int pt_pred_run(void* h);
// int pt_pred_out_ndim(void* h, int i);
// void pt_pred_out_dims(void* h, int i, int64_t* out);
// int pt_pred_out_is_int(void* h, int i);
// void pt_pred_out_copy(void* h, int i, void* out);
// void pt_pred_destroy(void* h);
import "C"

import (
	"errors"
	"runtime"
	"unsafe"
)

// Predictor runs a saved inference model through the native C++ engine.
type Predictor struct {
	h unsafe.Pointer
}

// NewPredictor loads the model named by cfg and prepares the executor.
func NewPredictor(cfg *Config) (*Predictor, error) {
	cdir := cString(cfg.ModelDir())
	defer freeCString(cdir)
	h := C.pt_pred_create(cdir)
	p := &Predictor{h: h}
	if msg := C.GoString(C.pt_pred_error(h)); msg != "" {
		C.pt_pred_destroy(h)
		return nil, errors.New("paddle: " + msg)
	}
	runtime.SetFinalizer(p, func(p *Predictor) { p.Destroy() })
	return p, nil
}

// Destroy releases the native predictor.
func (p *Predictor) Destroy() {
	if p.h != nil {
		C.pt_pred_destroy(p.h)
		p.h = nil
	}
}

// InputNames lists the model's feed variable names, in feed order.
func (p *Predictor) InputNames() []string {
	n := int(C.pt_pred_feed_count(p.h))
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.pt_pred_feed_name(p.h, C.int(i)))
	}
	runtime.KeepAlive(p)
	return out
}

// OutputNames lists the model's fetch variable names, in fetch order.
func (p *Predictor) OutputNames() []string {
	n := int(C.pt_pred_fetch_count(p.h))
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.pt_pred_fetch_name(p.h, C.int(i)))
	}
	runtime.KeepAlive(p)
	return out
}

// SetInput binds a tensor (float32 or int64, optionally lod-tagged) to
// the named feed variable. Returns an error when the data length does
// not match the shape (the C side copies Numel elements and would read
// past the Go slice otherwise).
func (p *Predictor) SetInput(name string, t *Tensor) error {
	if n := t.Numel(); (t.Ints != nil && int64(len(t.Ints)) != n) ||
		(t.Ints == nil && int64(len(t.Data)) != n) {
		return errors.New("paddle: SetInput " + name +
			": data length does not match shape numel")
	}
	cname := cString(name)
	defer freeCString(cname)
	dims := (*C.int64_t)(unsafe.Pointer(&t.Shape[0]))
	if t.Ints != nil {
		C.pt_pred_set_input_i64(p.h, cname, dims, C.int(len(t.Shape)),
			(*C.int64_t)(unsafe.Pointer(&t.Ints[0])))
	} else {
		C.pt_pred_set_input(p.h, cname, dims, C.int(len(t.Shape)),
			(*C.float)(unsafe.Pointer(&t.Data[0])))
	}
	if len(t.Lod) > 0 {
		C.pt_pred_set_input_lod(p.h, cname,
			(*C.int64_t)(unsafe.Pointer(&t.Lod[0])), C.int(len(t.Lod)))
	}
	runtime.KeepAlive(p)
	runtime.KeepAlive(t)
	return nil
}

// Run executes the model and returns every fetch output.
func (p *Predictor) Run() ([]*Tensor, error) {
	if C.pt_pred_run(p.h) != 0 {
		return nil, errors.New("paddle: " + C.GoString(C.pt_pred_error(p.h)))
	}
	n := int(C.pt_pred_fetch_count(p.h))
	outs := make([]*Tensor, n)
	for i := 0; i < n; i++ {
		nd := int(C.pt_pred_out_ndim(p.h, C.int(i)))
		shape := make([]int64, nd)
		if nd > 0 {
			C.pt_pred_out_dims(p.h, C.int(i),
				(*C.int64_t)(unsafe.Pointer(&shape[0])))
		}
		numel := int64(1)
		for _, d := range shape {
			numel *= d
		}
		t := &Tensor{Shape: shape}
		if C.pt_pred_out_is_int(p.h, C.int(i)) != 0 {
			t.Ints = make([]int64, numel)
			if numel > 0 {
				C.pt_pred_out_copy(p.h, C.int(i),
					unsafe.Pointer(&t.Ints[0]))
			}
		} else {
			t.Data = make([]float32, numel)
			if numel > 0 {
				C.pt_pred_out_copy(p.h, C.int(i),
					unsafe.Pointer(&t.Data[0]))
			}
		}
		outs[i] = t
	}
	runtime.KeepAlive(p)
	return outs, nil
}
