package paddle

// Tensor is a host-side dense tensor exchanged with the predictor.
// Float32 or int64 either way; Lod (level-1 offsets) marks packed
// sequence rows for the lod-aware kernels (sequence_pool,
// attention_lstm) — reference go/paddle/tensor.go ZeroCopyTensor role.
type Tensor struct {
	Shape []int64
	Data  []float32 // set for float inputs/outputs
	Ints  []int64   // set for int64 inputs/outputs
	Lod   []int64   // optional level-1 offsets ([0, n1, n1+n2, ...])
}

// NewTensor builds a float32 input tensor.
func NewTensor(shape []int64, data []float32) *Tensor {
	return &Tensor{Shape: shape, Data: data}
}

// NewIntTensor builds an int64 input tensor (sparse-id feeds).
func NewIntTensor(shape []int64, data []int64) *Tensor {
	return &Tensor{Shape: shape, Ints: data}
}

// SetLod attaches level-1 sequence offsets to the tensor.
func (t *Tensor) SetLod(offsets []int64) { t.Lod = offsets }

// Numel returns the element count implied by Shape.
func (t *Tensor) Numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}

// IsInt reports whether the tensor holds int64 data.
func (t *Tensor) IsInt() bool { return t.Ints != nil }
