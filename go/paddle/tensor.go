package paddle

// Tensor is a host-side dense tensor exchanged with the predictor.
// Float32 inputs only (the native engine's feed dtype; int64 feeds are
// cast server-side), float32 or int64 outputs.
type Tensor struct {
	Shape []int64
	Data  []float32 // set for float outputs/inputs
	Ints  []int64   // set for int64 outputs
}

// NewTensor builds a float32 input tensor.
func NewTensor(shape []int64, data []float32) *Tensor {
	return &Tensor{Shape: shape, Data: data}
}

// Numel returns the element count implied by Shape.
func (t *Tensor) Numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}

// IsInt reports whether the tensor holds int64 data.
func (t *Tensor) IsInt() bool { return t.Ints != nil }
