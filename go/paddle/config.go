// Package paddle is the Go inference client for paddle_tpu's native
// predictor (csrc/ptcore NaiveExecutor engine).
//
// Reference parity: go/paddle/{config,predictor,tensor}.go — a cgo wrapper
// over the C ABI. Here the ABI is ptcore's pt_pred_* surface
// (csrc/ptcore/executor.cc:628); build libptcore.so first (cmake+ninja in
// csrc/, or the auto-build in paddle_tpu.core.native), then:
//
//	CGO_CFLAGS="-I${REPO}/go/paddle" \
//	CGO_LDFLAGS="-L${REPO}/csrc/build/lib -lptcore" \
//	go build ./...
package paddle

// #cgo LDFLAGS: -lptcore
// #include <stdint.h>
// #include <stdlib.h>
// void* pt_pred_create(const char* model_dir);
// const char* pt_pred_error(void* h);
// int pt_pred_feed_count(void* h);
// const char* pt_pred_feed_name(void* h, int i);
// int pt_pred_fetch_count(void* h);
// const char* pt_pred_fetch_name(void* h, int i);
import "C"

import "unsafe"

// Config selects a saved-inference-model directory (the durable
// `__model__` + params artifact written by save_inference_model /
// paddle.jit.save).
type Config struct {
	modelDir string
}

func NewConfig() *Config { return &Config{} }

// SetModel points the config at a model directory.
func (c *Config) SetModel(modelDir string) { c.modelDir = modelDir }

// ModelDir returns the configured model directory.
func (c *Config) ModelDir() string { return c.modelDir }

func cString(s string) *C.char { return C.CString(s) }

func freeCString(p *C.char) { C.free(unsafe.Pointer(p)) }
