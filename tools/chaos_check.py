#!/usr/bin/env python
"""Chaos matrix CI gate: every registered fault point x every
applicable action against a tiny model, bounded by wall timeouts.

For each cell the harness arms ONE injection plan, drives the
subsystem (serving pool / checkpoint manager / dataloader), and
requires the fault-tolerance contract to hold:

  * serving.* / scheduler.admit — every submitted future RESOLVES
    (result or exception, never a hang) and the pool serves a clean
    batch after disarm;
  * checkpoint.write/read — a raise leaves no torn step, a corrupt
    plan is detected + restore falls back, a delay just slows;
  * dataloader.next — a raise surfaces to the caller deterministically.

Each cell runs on a worker thread with a hard join timeout: a hung
cell is reported as HANG and the run exits nonzero. Usage:

    JAX_PLATFORMS=cpu python tools/chaos_check.py [--timeout-s 120]
    python tools/chaos_check.py --list          # print the matrix
    python tools/chaos_check.py --trace         # + chrome-trace
                                                #   artifact per cell

The equivalent in-suite coverage is `pytest -m chaos`; this script is
the standalone gate (no pytest, explicit exit code) for CI cron.
"""
import argparse
import os
import sys
import threading
import time
import traceback

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _small_engine(seed=7, **kw):
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import ServingEngine

    np.random.seed(seed)
    layer = TransformerDecoderLayer(32, 2, 64, dropout=0.0)
    dec = TransformerDecoder(layer, 2)
    dec.eval()
    embed = nn.Embedding(17, 32)
    proj = nn.Linear(32, 17)
    kw.setdefault("max_attempts", 2)
    kw.setdefault("backoff_base_s", 0.0)
    eng = ServingEngine(dec, embed, proj, num_slots=4, max_len=32, **kw)
    return eng


def _requests(n, seed):
    from paddle_tpu.serving import Request

    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        P = int(rs.randint(1, 6))
        prompt = rs.randint(2, 17, (P,)).astype(np.int32)
        prompt[0] = 0
        mem = rs.randn(4, 32).astype("f4")
        out.append(Request(prompt, mem, max_new_tokens=int(
            rs.randint(2, 8)), eos_id=1))
    return out


def _drive_serving(point, action):
    """One serving cell: 8 requests with the plan armed, then a clean
    batch. Raises on any unresolved future."""
    from paddle_tpu.serving import Scheduler
    from paddle_tpu.testing import faults

    eng = _small_engine()
    sched = Scheduler(max_queue=64)
    plan = (dict(action="delay", delay_s=0.02, on="every", k=3)
            if action == "delay" else dict(on="every", k=3))
    inj = faults.inject(point, **plan)
    accepted = []
    try:
        for r in _requests(8, seed=11):
            try:
                sched.submit(r)
            except faults.InjectedFault:
                continue             # admission loss: caller informed
            accepted.append(r)
        it = 0
        while sched.depth() > 0 or eng.occupancy() > 0:
            eng.run_iteration(sched)
            it += 1
            if it > 2000:
                raise RuntimeError("no convergence under faults")
        fired = inj.fired
    finally:
        faults.reset()
    if not fired:
        raise RuntimeError(f"plan on {point} never fired")
    for r in accepted:
        if not r.future.done():
            raise RuntimeError(f"hung future {r.id} ({point}/{action})")
    # pool must still serve clean work
    sched2 = Scheduler(max_queue=16)
    clean = _requests(3, seed=13)
    for r in clean:
        sched2.submit(r)
    it = 0
    while sched2.depth() > 0 or eng.occupancy() > 0:
        eng.run_iteration(sched2)
        it += 1
        if it > 500:
            raise RuntimeError("pool dead after disarm")
    for r in clean:
        if not r.result(timeout=0).ok:
            raise RuntimeError("clean request failed after disarm")


def _drive_paged_spec(point, action):
    """The paged-verify fault cell: serving.decode_step armed on a
    PAGED pool running SPECULATIVE decode (the pverify program path).
    Exhausted retries must evict the in-flight requests with partials,
    the allocator free list must return to its initial state after the
    drain (no page leaked across the eviction/reset), and the revived
    pool must serve clean spec traffic."""
    from paddle_tpu.serving import Scheduler
    from paddle_tpu.testing import faults

    point = point.split("[", 1)[0]     # cell label -> real fault point
    eng = _small_engine(paged=True, page_size=8, spec_k=4)
    sched = Scheduler(max_queue=64)
    plan = (dict(action="delay", delay_s=0.02, on="every", k=3)
            if action == "delay" else dict(on="every", k=3))
    inj = faults.inject(point, **plan)
    accepted = []
    try:
        for r in _requests(8, seed=17):
            sched.submit(r)
            accepted.append(r)
        it = 0
        while sched.depth() > 0 or eng.occupancy() > 0:
            eng.run_iteration(sched)
            it += 1
            if it > 2000:
                raise RuntimeError("no convergence under faults")
        fired = inj.fired
    finally:
        faults.reset()
    if not fired:
        raise RuntimeError(f"plan on {point} never fired")
    for r in accepted:
        if not r.future.done():
            raise RuntimeError(f"hung future {r.id} ({point}/{action})")
    # leak check: every page back on the free list after the drain
    eng.flush_prefix_cache()
    eng._alloc.check()
    if eng._alloc.pages_free != eng.num_pages:
        raise RuntimeError(
            f"page leak: {eng._alloc.pages_free} free of "
            f"{eng.num_pages} after drain")
    # pool revives: clean spec traffic completes
    sched2 = Scheduler(max_queue=16)
    clean = _requests(3, seed=19)
    for r in clean:
        sched2.submit(r)
    it = 0
    while sched2.depth() > 0 or eng.occupancy() > 0:
        eng.run_iteration(sched2)
        it += 1
        if it > 500:
            raise RuntimeError("pool dead after disarm")
    for r in clean:
        if not r.result(timeout=0).ok:
            raise RuntimeError("clean request failed after disarm")


def _long_requests(n, seed, pmin=9, pmax=14):
    from paddle_tpu.serving import Request

    rs = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        P = int(rs.randint(pmin, pmax + 1))
        prompt = rs.randint(2, 17, (P,)).astype(np.int32)
        prompt[0] = 0
        mem = rs.randn(4, 32).astype("f4")
        out.append(Request(prompt, mem, max_new_tokens=int(
            rs.randint(2, 8)), eos_id=1))
    return out


def _drive_chunked(point, action):
    """serving.prefill_chunk cells: a paged pool with chunked prefill
    armed, faults landing MID-CHUNK-SEQUENCE (the slot holds a
    partially-prefilled prompt when the fault fires). Exhausted
    retries must fail only that request, release its pages, and leave
    the pool serving; after the drain the free list is back to initial
    and the revived pool completes clean chunked traffic."""
    from paddle_tpu.serving import Scheduler
    from paddle_tpu.testing import faults

    eng = _small_engine(paged=True, page_size=4, num_pages=48,
                        prefill_chunk=4)
    sched = Scheduler(max_queue=64)
    plan = (dict(action="delay", delay_s=0.02, on="every", k=3)
            if action == "delay" else dict(on="every", k=3))
    inj = faults.inject(point, **plan)
    accepted = []
    try:
        for r in _long_requests(8, seed=29):
            sched.submit(r)
            accepted.append(r)
        it = 0
        while sched.depth() > 0 or eng.occupancy() > 0:
            eng.run_iteration(sched)
            it += 1
            if it > 2000:
                raise RuntimeError("no convergence under faults")
        fired = inj.fired
    finally:
        faults.reset()
    if not fired:
        raise RuntimeError(f"plan on {point} never fired")
    for r in accepted:
        if not r.future.done():
            raise RuntimeError(f"hung future {r.id} ({point}/{action})")
    if action == "delay":
        for r in accepted:
            if not r.result(timeout=0).ok:
                raise RuntimeError("delay-only chunk fault failed a "
                                   "request")
    # leak check: evicted mid-chunk slots released every page
    eng.flush_prefix_cache()
    eng._alloc.check()
    if eng._alloc.pages_free != eng.num_pages:
        raise RuntimeError(
            f"page leak: {eng._alloc.pages_free} free of "
            f"{eng.num_pages} after chunked-prefill chaos")
    # pool revives: clean chunked traffic completes
    sched2 = Scheduler(max_queue=16)
    clean = _long_requests(3, seed=31)
    for r in clean:
        sched2.submit(r)
    it = 0
    while sched2.depth() > 0 or eng.occupancy() > 0:
        eng.run_iteration(sched2)
        it += 1
        if it > 500:
            raise RuntimeError("pool dead after disarm")
    for r in clean:
        if not r.result(timeout=0).ok:
            raise RuntimeError("clean request failed after disarm")
    if eng.metrics.chunks < 1:
        raise RuntimeError("chunked prefill never engaged")


def _drive_preempt(point, action):
    """serving.preempt cells: a full 2-slot paged pool running batch
    work when interactive requests arrive through the
    ShapingScheduler. The fault point fires BEFORE preemption mutates
    anything, so an injected raise must abort that preemption cleanly
    (no slot half-evicted) while every future still resolves OK; the
    free list returns to initial and the pool revives."""
    from paddle_tpu.serving import (Request, Scheduler,
                                    ShapingScheduler)
    from paddle_tpu.testing import faults

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import ServingEngine

    np.random.seed(7)
    layer = TransformerDecoderLayer(32, 2, 64, dropout=0.0)
    dec = TransformerDecoder(layer, 2)
    dec.eval()
    embed = nn.Embedding(17, 32)
    proj = nn.Linear(32, 17)
    eng = ServingEngine(dec, embed, proj, num_slots=2, max_len=32,
                        paged=True, page_size=4, num_pages=48,
                        max_attempts=2, backoff_base_s=0.0)
    sched = ShapingScheduler(max_queue=64, metrics=eng.metrics)
    rs = np.random.RandomState(37)

    def mk(pmin, pmax, slo):
        P = int(rs.randint(pmin, pmax + 1))
        prompt = rs.randint(2, 17, (P,)).astype(np.int32)
        prompt[0] = 0
        mem = rs.randn(4, 32).astype("f4")
        return Request(prompt, mem, max_new_tokens=int(
            rs.randint(4, 10)), eos_id=1, slo=slo)

    plan = (dict(action="delay", delay_s=0.02, on="every", k=2)
            if action == "delay" else dict(on="every", k=2))
    inj = faults.inject(point, **plan)
    reqs = []
    try:
        for _ in range(3):
            r = mk(5, 9, "batch")
            sched.submit(r)
            reqs.append(r)
        for _ in range(2):       # fill the pool with batch slots
            eng.run_iteration(sched)
        for _ in range(4):
            r = mk(1, 4, "interactive")
            sched.submit(r)
            reqs.append(r)
        it = 0
        while sched.depth() > 0 or eng.occupancy() > 0:
            eng.run_iteration(sched)
            it += 1
            if it > 2000:
                raise RuntimeError("no convergence under faults")
        fired = inj.fired
    finally:
        faults.reset()
    if not fired:
        raise RuntimeError(f"plan on {point} never fired")
    for r in reqs:
        if not r.future.done():
            raise RuntimeError(f"hung future {r.id} ({point}/{action})")
        if not r.result(timeout=0).ok:
            raise RuntimeError(
                f"request {r.id} failed under {action}: an aborted "
                f"preemption must leave the victim running")
    # leak check: preempted slots' pages all released or in the trie
    eng.flush_prefix_cache()
    eng._alloc.check()
    if eng._alloc.pages_free != eng.num_pages:
        raise RuntimeError(
            f"page leak: {eng._alloc.pages_free} free of "
            f"{eng.num_pages} after preemption chaos")
    # pool revives on the plain FIFO
    sched2 = Scheduler(max_queue=16)
    clean = _requests(3, seed=41)
    for r in clean:
        sched2.submit(r)
    it = 0
    while sched2.depth() > 0 or eng.occupancy() > 0:
        eng.run_iteration(sched2)
        it += 1
        if it > 500:
            raise RuntimeError("pool dead after disarm")
    for r in clean:
        if not r.result(timeout=0).ok:
            raise RuntimeError("clean request failed after disarm")


def _drive_adapter_load(point, action):
    """serving.adapter_load cells: the multi-tenant pool under bank
    hot-load faults. `transient` (fires once) must be retried by the
    join's guard and the tenant served NORMALLY; `raise` (persistent)
    must isolate ONLY that tenant's requests — eager fallback serves
    them on the base model while co-resident base/other-tenant
    requests are untouched; `delay` just slows. After the drain the
    pool's refcounts and free list are back to initial (leak-free),
    and clean adapter traffic serves after disarm."""
    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.serving import (AdapterPool, Request, Scheduler,
                                    ServingEngine)
    from paddle_tpu.testing import faults

    np.random.seed(7)
    layer = TransformerDecoderLayer(32, 2, 64, dropout=0.0)
    dec = TransformerDecoder(layer, 2)
    dec.eval()
    embed = nn.Embedding(17, 32)
    proj = nn.Linear(32, 17)
    pool = AdapterPool(dec, capacity=3, rank=4)
    pool.register_random("t1", seed=1)
    pool.register_random("t2", seed=2)
    eng = ServingEngine(dec, embed, proj, num_slots=4, max_len=32,
                        adapters=pool, eager_fallback=True,
                        max_attempts=2, backoff_base_s=0.0)
    sched = Scheduler(max_queue=64)
    if action == "delay":
        plan = dict(action="delay", delay_s=0.02, on="every", k=2)
    elif action == "transient":
        plan = dict(on="nth", n=1, max_fires=1)
    else:
        plan = dict(on="always")
    inj = faults.inject(point, **plan)
    rs = np.random.RandomState(23)
    reqs = []
    try:
        for name in (None, "t1", "t2", None, "t1", "t2"):
            P = int(rs.randint(1, 6))
            prompt = rs.randint(2, 17, (P,)).astype(np.int32)
            prompt[0] = 0
            mem = rs.randn(4, 32).astype("f4")
            r = Request(prompt, mem, max_new_tokens=int(
                rs.randint(2, 8)), eos_id=1, adapter=name)
            sched.submit(r)
            reqs.append((r, name))
        it = 0
        while sched.depth() > 0 or eng.occupancy() > 0:
            eng.run_iteration(sched)
            it += 1
            if it > 2000:
                raise RuntimeError("no convergence under faults")
        fired = inj.fired
    finally:
        faults.reset()
    if not fired:
        raise RuntimeError(f"plan on {point} never fired")
    for r, name in reqs:
        if not r.future.done():
            raise RuntimeError(f"hung future {r.id} ({point}/{action})")
        if not r.result(timeout=0).ok:
            raise RuntimeError(
                f"request {r.id} (adapter={name}) failed under "
                f"{action}: isolation demands it resolve (fallback "
                f"serves the base model)")
    if action == "raise":
        if eng.metrics.fallbacks < 1:
            raise RuntimeError("persistent load fault never degraded "
                               "to the eager base-model path")
    # leak-free: every bank reference released, invariants hold
    pool.check()
    if pool.refcount.sum() != 0:
        raise RuntimeError(f"adapter refcount leak: {pool.refcount}")
    # clean adapter traffic serves after disarm
    sched2 = Scheduler(max_queue=16)
    prompt = np.asarray([0, 3, 5], np.int32)
    clean = Request(prompt, rs.randn(4, 32).astype("f4"),
                    max_new_tokens=4, eos_id=1, adapter="t1")
    sched2.submit(clean)
    it = 0
    while sched2.depth() > 0 or eng.occupancy() > 0:
        eng.run_iteration(sched2)
        it += 1
        if it > 500:
            raise RuntimeError("pool dead after disarm")
    if not clean.result(timeout=0).ok:
        raise RuntimeError("clean adapter request failed after disarm")
    if pool.loads < 1:
        raise RuntimeError("no successful adapter load after disarm")


def _drive_checkpoint(point, action):
    import shutil
    import tempfile

    from paddle_tpu.io.checkpoint import (CheckpointCorrupt,
                                          CheckpointManager)
    from paddle_tpu.testing import faults

    d = tempfile.mkdtemp(prefix="chaos_ckpt_")
    try:
        m = CheckpointManager(d, max_to_keep=None)
        m.save(0, {"w": np.arange(8)})
        plan = (dict(action="delay", delay_s=0.02) if action == "delay"
                else dict(action=action))
        with faults.inject(point, on="always", **plan):
            if point == "checkpoint.write":
                if action == "raise":
                    try:
                        m.save(1, {"w": np.arange(8) + 1})
                        raise RuntimeError("torn save did not raise")
                    except faults.InjectedFault:
                        pass
                    if m.all_steps() != [0]:
                        raise RuntimeError("torn step leaked")
                else:
                    m.save(1, {"w": np.arange(8) + 1})
            else:   # checkpoint.read
                if action == "raise":
                    try:
                        m.restore(step=0)
                        raise RuntimeError("read fault did not raise")
                    except faults.InjectedFault:
                        pass
                elif action == "corrupt":
                    try:
                        m.restore(step=0)
                        raise RuntimeError("corrupt read undetected")
                    except CheckpointCorrupt:
                        pass
                else:
                    m.restore(step=0)
        # recovery: restore always lands on a valid step after disarm
        st = m.restore()
        expect = 0 if (point, action) != ("checkpoint.write", "delay") \
            else 1
        if int(np.asarray(st["w"])[0]) != expect:
            raise RuntimeError(f"recovered wrong step: {st['w']}")
        if point == "checkpoint.write" and action == "corrupt":
            if m.valid_steps() != [0]:
                raise RuntimeError("corrupt step counted as valid")
    finally:
        faults.reset()
        shutil.rmtree(d, ignore_errors=True)


def _drive_dataloader(point, action):
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.testing import faults

    ds = TensorDataset([np.arange(12, dtype=np.float32).reshape(12, 1)])
    dl = DataLoader(ds, batch_size=2, shuffle=False)
    plan = (dict(action="delay", delay_s=0.02, on="every", k=2)
            if action == "delay" else dict(on="nth", n=2))
    with faults.inject(point, **plan):
        try:
            n = sum(1 for _ in dl)
            if action == "raise":
                raise RuntimeError("dataloader fault did not surface")
            if n != 6:
                raise RuntimeError(f"lost batches under delay: {n}")
        except faults.InjectedFault:
            if action != "raise":
                raise
    faults.reset()
    if sum(1 for _ in dl) != 6:
        raise RuntimeError("dataloader broken after disarm")


def _drive_aot_cache(point, action):
    """tuning.cache_load cell: populate a persistent AOT cache, then
    restart-precompile with the plan armed. Corrupt blobs must read
    as CRC misses (fresh compile, cache_errors counted, serving
    unaffected); a delay just slows; a raise propagates (the chaos
    harness's own signal) and the NEXT unfaulted precompile still
    works off the healed cache."""
    import shutil
    import tempfile

    from paddle_tpu.serving import Scheduler
    from paddle_tpu.testing import faults

    d = tempfile.mkdtemp(prefix="chaos_aot_")
    try:
        eng = _small_engine()
        eng.precompile((4, 32), dtype="float32", prompt_buckets=(4,),
                       cache=d)
        plan = (dict(action="delay", delay_s=0.02) if action == "delay"
                else dict(action=action))
        eng2 = _small_engine()
        with faults.inject(point, on="always", **plan):
            if action == "raise":
                try:
                    eng2.precompile((4, 32), dtype="float32",
                                    prompt_buckets=(4,), cache=d)
                    raise RuntimeError("load fault did not surface")
                except faults.InjectedFault:
                    pass
            else:
                rep = eng2.precompile((4, 32), dtype="float32",
                                      prompt_buckets=(4,), cache=d)
                if action == "corrupt" and not rep["cache_errors"]:
                    raise RuntimeError("corrupt entries undetected")
        faults.reset()
        # the pool must serve after the chaos pass, and a clean
        # restart must be fully warm again (healed cache)
        eng3 = _small_engine()
        rep3 = eng3.precompile((4, 32), dtype="float32",
                               prompt_buckets=(4,), cache=d)
        if not rep3["warm"]:
            raise RuntimeError(f"cache did not heal: {rep3}")
        sched = Scheduler(max_queue=16)
        reqs = _requests(3, seed=13)
        for r in reqs:
            sched.submit(r)
        eng3.serve_until_idle(sched, max_iterations=500)
        for r in reqs:
            if not r.result(timeout=0).ok:
                raise RuntimeError("request failed on warm pool")
    finally:
        faults.reset()
        shutil.rmtree(d, ignore_errors=True)


MATRIX = (
    [("scheduler.admit", a, _drive_serving) for a in ("raise", "delay")]
    + [("serving.slot_join", a, _drive_serving)
       for a in ("raise", "delay")]
    + [("serving.prefill", a, _drive_serving)
       for a in ("raise", "delay")]
    + [("serving.decode_step", a, _drive_serving)
       for a in ("raise", "delay")]
    + [("serving.decode_step[pspec]", a, _drive_paged_spec)
       for a in ("raise", "delay")]
    + [("serving.prefill_chunk", a, _drive_chunked)
       for a in ("raise", "delay")]
    + [("serving.preempt", a, _drive_preempt)
       for a in ("raise", "delay")]
    + [("serving.adapter_load", a, _drive_adapter_load)
       for a in ("raise", "delay", "transient")]
    + [("checkpoint.write", a, _drive_checkpoint)
       for a in ("raise", "delay", "corrupt")]
    + [("checkpoint.read", a, _drive_checkpoint)
       for a in ("raise", "delay", "corrupt")]
    + [("dataloader.next", a, _drive_dataloader)
       for a in ("raise", "delay")]
    + [("tuning.cache_load", a, _drive_aot_cache)
       for a in ("raise", "delay", "corrupt")]
)


def run_cell(point, action, fn, timeout_s, trace_dir=None):
    box = {}

    def work():
        tr = None
        if trace_dir:
            from paddle_tpu.profiler import trace as T

            T.end_session()   # clear a session a hung cell leaked
            tr = T.start_session()
        try:
            fn(point, action)
            box["ok"] = True
        except BaseException as e:
            box["err"] = f"{type(e).__name__}: {e}"
            box["tb"] = traceback.format_exc()
        finally:
            if tr is not None:
                from paddle_tpu.profiler import trace as T

                T.end_session()
                path = os.path.join(
                    trace_dir,
                    f"chaos_{point.replace('.', '_')}_{action}.json")
                tr.export_chrome_trace(path)
                box["trace"] = path

    t = threading.Thread(target=work, daemon=True)
    t0 = time.monotonic()
    t.start()
    t.join(timeout_s)
    dt = time.monotonic() - t0
    if t.is_alive():
        return "HANG", dt, f"cell still running after {timeout_s}s"
    if "err" in box:
        return "FAIL", dt, box["err"]
    return "ok", dt, box.get("trace", "")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout-s", type=float, default=180.0,
                    help="hard wall budget per matrix cell")
    ap.add_argument("--points", default="",
                    help="comma-separated substring filter on points")
    ap.add_argument("--list", action="store_true",
                    help="print the matrix and exit")
    ap.add_argument("--trace", action="store_true",
                    help="write a chrome-trace artifact per cell "
                         "(inspect with tools/trace_report.py or "
                         "Perfetto)")
    ap.add_argument("--trace-dir",
                    default="/tmp/paddle_tpu_chaos_traces",
                    help="directory for --trace artifacts")
    args = ap.parse_args(argv)
    trace_dir = None
    if args.trace:
        trace_dir = args.trace_dir
        os.makedirs(trace_dir, exist_ok=True)
    cells = [(p, a, f) for p, a, f in MATRIX
             if not args.points or any(s and s in p for s in
                                       args.points.split(","))]
    if args.list:
        for p, a, _ in cells:
            print(f"{p} x {a}")
        return 0
    failures = 0
    for p, a, f in cells:
        status, dt, msg = run_cell(p, a, f, args.timeout_s,
                                   trace_dir=trace_dir)
        print(f"{p:24s} x {a:8s} {status:5s} {dt:7.2f}s  {msg}")
        if status != "ok":
            failures += 1
    print(f"\n{len(cells) - failures}/{len(cells)} cells ok")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
