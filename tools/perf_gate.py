#!/usr/bin/env python
"""Noise-aware perf-regression gate over the committed baselines.

The BENCH_r01 -> r05 trajectory (4.44x on ernie_base) was guarded only
by hand-read JSON: a silent perf regression would ship. This gate
turns the committed `OP_BENCH.json` / `BENCH_DETAILS.json` baselines
into a standing assertion: re-measure a row set fresh, compare each
row against its baseline under a per-row relative tolerance
(median-of-k on the fresh side; the op harness itself medians pair
slopes), exit nonzero on regression, and write the full comparison as
`PERF_GATE.json` next to the baselines.

Row semantics:
  op rows     OP_BENCH.json `ops[name].step_us` — LOWER is better; a
              row regresses when fresh > tol x baseline.
  bench rows  BENCH_DETAILS.json `[metric].value` (the headline
              speedup/throughput) — HIGHER is better; a row regresses
              when fresh < baseline / tol. A baseline row inflated 2x
              (or a real 2x slowdown) fails under the default 1.5x
              tolerance.

Usage:
  python tools/perf_gate.py --quick            # 2-row op smoke (CI /
                                               # tier-1; seconds)
  python tools/perf_gate.py                    # default row set (op
                                               # quick-8; minutes)
  python tools/perf_gate.py --ops matmul,abs --bench fused_optimizer
  python tools/perf_gate.py --allow matmul     # tolerate named rows
  python tools/perf_gate.py --op-baseline alt.json --out gate.json

Noise discipline (1-core CPU box): fresh measurements are the MEDIAN
of k runs (--k, default 3); tolerances default loose (op 2x — the
scripts/ci.sh precedent — and bench 1.5x) and are per-row overridable
via --tol-op/--tol-bench. Allowlisted rows are still measured and
recorded, just not fatal — the paper trail survives in PERF_GATE.json.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

OP_BASELINE = os.path.join(REPO, "OP_BENCH.json")
BENCH_BASELINE = os.path.join(REPO, "BENCH_DETAILS.json")
GATE_OUT = os.path.join(REPO, "PERF_GATE.json")

#: the tier-1 smoke subset: two cheap, committed op rows (sub-ms
#: steps, sub-second compiles) so the gate ITSELF is exercised on
#: every CI run without denting the budget
QUICK_OPS = ("sequence_mask", "tile")

#: default full-run row set: the op harness's quick-8 plus the bench
#: rows cheap enough to re-measure in minutes (the serving rows are
#: wall-clock-shaped and re-anchored per PR instead)
DEFAULT_BENCH = ("fused_optimizer",)

#: speculative-decoding rows folded into the full-run default (PR 10):
#: one verify row and its plain-step pair, so a regression in the
#: k-token verify path (the spec hot kernel) fails the gate
SPEC_OPS = ("spec_decode_plain_b1_L2048",
            "spec_decode_verify_k4_b1_L2048",
            # the paged spec pair (PR 13): the paged decode step and
            # the paged k-token verify it widens into — a regression
            # in the block-table verify path fails the gate
            "paged_decode_b8_L2048_p16_f32",
            "paged_verify_k4_f32")

#: multi-tenant rows folded into the full-run default (PR 15): the
#: decode-shaped base linear and its adapter-carrying pair (the
#: step_us gap is the per-dispatch cost of carrying LoRA banks — a
#: regression here taxes EVERY multi-tenant decode step), plus the
#: int8-vs-f32 weight matmul row (paired in-row via measure_pair)
LORA_OPS = ("lora_base_b8", "lora_decode_r8_b8", "int8_matmul_vs_f32")

#: radix prefix-attach pair folded into the full-run default (PR 16):
#: the shallow and deep matched-depth attach rows (tail-only verify
#: attention through the clipped page table, measured paired in-row
#: against the same-depth whole-prompt prefill — the int8_matmul
#: precedent). step_us is the tail side, so a regression in the
#: pattach hot path — the thing every partial radix hit rides — fails
#: the gate even while the whole-prompt path stays fast
RADIX_OPS = ("prefix_attach_m4_t1", "prefix_attach_m16_t1")

#: zero-copy join rows folded into the full-run default (PR 17): the
#: dense slot splice and the paged page scatter, each measured paired
#: in-row DONATED vs undonated (measure_pair). step_us is the donated
#: side — the write every join in the family now dispatches — so a
#: regression in the in-place path fails the gate even if the old
#: copying path would have hidden it
JOIN_OPS = ("join_inplace_vs_copy_dense", "join_inplace_vs_copy_paged")

#: tuned-vs-fallback rows folded into the full-run default (PR 11):
#: the autotuned flash_decode config must NEVER be slower than the
#: hand-picked constants it replaced. Both sides are measured fresh,
#: PAIRED (op_bench.measure_pair — the only stable way to compare
#: sub-2x deltas on this 1-core box); no committed baseline involved.
#: On an untuned device the table resolves to the fallback itself, so
#: the row times the same config twice and trivially holds — the gate
#: only bites where a sweep actually installed a different config.
TUNING_ROWS = (("flash_decode", (64, 2048, "float32")),)


# ----------------------------------------------------------------------
# pure comparison core (unit-tested directly; no measurement involved)
# ----------------------------------------------------------------------

def evaluate_row(direction, baseline, fresh, tol):
    """One row's verdict: "pass" or "regress". `tol` is a ratio > 1;
    "lower" rows regress when fresh > tol * baseline, "higher" rows
    when fresh < baseline / tol."""
    if direction not in ("lower", "higher"):
        raise ValueError(f"direction must be lower|higher: {direction}")
    if tol <= 1.0:
        raise ValueError(f"tol must be > 1, got {tol}")
    if baseline is None or fresh is None or baseline <= 0:
        return "missing"
    if direction == "lower":
        return "regress" if fresh > tol * baseline else "pass"
    return "regress" if fresh < baseline / tol else "pass"


def gate(rows, allowlist=()):
    """Apply verdicts + the allowlist to measured rows. Each row dict
    needs {name, direction, baseline, fresh, tol}; rows missing either
    side get status "missing-row" (fatal: a silently vanished baseline
    row must not pass as green). Returns the PERF_GATE.json payload."""
    allow = set(allowlist)
    out_rows = []
    regressions = []
    missing = []
    for r in rows:
        row = dict(r)
        verdict = evaluate_row(r["direction"], r.get("baseline"),
                               r.get("fresh"), r["tol"])
        if verdict == "missing":
            row["status"] = "missing-row"
            missing.append(r["name"])
        elif verdict == "regress" and r["name"] in allow:
            row["status"] = "allowlisted"
        elif verdict == "regress":
            row["status"] = "regress"
            regressions.append(r["name"])
        else:
            row["status"] = "pass"
        b, f = r.get("baseline"), r.get("fresh")
        if b and f:
            row["ratio"] = round(f / b, 4)
        out_rows.append(row)
    return {"rows": out_rows,
            "regressions": regressions,
            "missing": missing,
            "ok": not regressions and not missing}


# ----------------------------------------------------------------------
# fresh measurement
# ----------------------------------------------------------------------

def measure_op(name, k=3, quiet=True):
    """Median-of-k fresh step_us for one op_bench config."""
    import op_bench

    cfgs = {c[0]: c[1:] for c in op_bench._configs()}
    if name not in cfgs:
        return None
    builder, *rest = cfgs[name]
    opts = rest[0] if rest else {}
    vals = []
    for _ in range(int(k)):
        if getattr(builder, "_direct", False):
            r = builder()
        else:
            r = op_bench.bench_one(name, builder, **opts)
        if "step_us" not in r:
            return None
        vals.append(float(r["step_us"]))
        if not quiet:
            print(f"  {name}: {r['step_us']}us", file=sys.stderr)
    return statistics.median(vals)


def measure_bench(metric, k=1, quiet=True):
    """Median-of-k fresh headline `value` for one bench.py config."""
    import bench

    fn = dict([
        ("mnist", bench._mnist_static), ("resnet50", bench._resnet50),
        ("ernie", bench._ernie), ("ctr_ps", bench._ctr_dnn_ps),
        ("long_context", bench._long_context_attention),
        ("ernie_long", bench._ernie_long),
        ("packed_varlen", bench._packed_varlen),
        ("fused_optimizer", bench._fused_optimizer),
        ("decode_throughput", bench._decode_throughput),
        ("cold_start", bench._cold_start),
        ("serving_throughput", bench._serving_throughput),
        ("serving_paged", bench._serving_paged),
        ("serving_radix", bench._serving_radix),
        ("serving_slo", bench._serving_slo),
        ("serving_sharded", bench._serving_sharded),
    ]).get(metric)
    if fn is None:
        return None
    vals = []
    for _ in range(int(k)):
        r = fn()
        if "value" not in r:
            return None
        vals.append(float(r["value"]))
        if not quiet:
            print(f"  {metric}: {r['value']}", file=sys.stderr)
    return statistics.median(vals)


def measure_tuning_row(kernel, key, *, steps=12, k=5, batch=4,
                       heads=4, quiet=True):
    """(fallback_s, tuned_s) for one tuning-table row, measured PAIRED
    via op_bench.measure_pair over the real dispatch path. The tuned
    side is whatever the active table resolves for (kernel, key) on
    this device (the fallback itself when untuned)."""
    import op_bench

    from paddle_tpu.tuning import autotune as AT
    from paddle_tpu.tuning import table as TBL

    fb = AT.fallback_config(kernel, key)
    tuned = TBL.lookup(kernel, key) or fb
    tuned = {kk: tuned[kk] for kk in TBL.KERNEL_KNOBS[kernel]
             if kk in tuned} or fb
    run_fb = AT.build_runner(kernel, key, fb, batch, heads)
    run_tuned = AT.build_runner(kernel, key, tuned, batch, heads)
    dt_fb, dt_tuned = op_bench.measure_pair(run_fb, run_tuned,
                                            steps=steps, k=k)
    if not quiet:
        print(f"  tuning:{kernel}:{TBL.key_str(key)} fallback "
              f"{dt_fb * 1e6:.1f}us ({fb}) tuned "
              f"{dt_tuned * 1e6:.1f}us ({tuned})", file=sys.stderr)
    return dt_fb, dt_tuned


def build_tuning_rows(tuning_rows, tol, k=5, quiet=True,
                      measure=measure_tuning_row):
    """Tuned-config-never-slower rows: baseline = the hand-picked
    fallback's PAIRED measurement, fresh = the tuned config's —
    direction 'lower', so a tuned entry slower than the constants it
    replaced regresses. `measure` is injectable for unit tests."""
    rows = []
    for kernel, key in tuning_rows:
        name = "tuning:" + kernel + ":" + "/".join(str(x) for x in key)
        try:
            dt_fb, dt_tuned = measure(kernel, key, k=k, quiet=quiet)
        except Exception as e:
            rows.append({"name": name, "direction": "lower",
                         "unit": "paired_us", "tol": tol,
                         "baseline": None, "fresh": None,
                         "error": f"{type(e).__name__}: {e}"})
            continue
        rows.append({"name": name, "direction": "lower",
                     "unit": "paired_us", "tol": tol,
                     "baseline": round(dt_fb * 1e6, 2),
                     "fresh": round(dt_tuned * 1e6, 2)})
    return rows


def build_rows(op_names, bench_names, op_base, bench_base, tol_op,
               tol_bench, k, quiet=True):
    """Measure every selected row fresh and pair it with its
    baseline."""
    rows = []
    for name in op_names:
        b = (op_base.get("ops", {}).get(name, {}) or {}).get("step_us")
        rows.append({"name": f"op:{name}", "direction": "lower",
                     "unit": "step_us", "tol": tol_op,
                     "baseline": float(b) if b else None,
                     "fresh": measure_op(name, k=k, quiet=quiet)})
    for name in bench_names:
        b = (bench_base.get(name, {}) or {}).get("value")
        rows.append({"name": f"bench:{name}", "direction": "higher",
                     "unit": "value", "tol": tol_bench,
                     "baseline": float(b) if b else None,
                     "fresh": measure_bench(name, k=max(1, k // 3 or 1),
                                            quiet=quiet)})
    return rows


def run_gate(op_names=(), bench_names=(), *, op_baseline=OP_BASELINE,
             bench_baseline=BENCH_BASELINE, tol_op=2.0, tol_bench=1.5,
             k=3, allowlist=(), out=GATE_OUT, quiet=True,
             tuning_rows=(), tol_tuning=1.5):
    """Measure, compare, persist. Returns the gate payload (and writes
    it to `out`); callers decide the exit code from payload["ok"]."""

    def _load(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            return {}

    op_base = _load(op_baseline)
    bench_base = _load(bench_baseline)
    rows = build_rows(op_names, bench_names, op_base, bench_base,
                      tol_op, tol_bench, k, quiet=quiet)
    rows += build_tuning_rows(tuning_rows, tol_tuning, k=max(3, k),
                              quiet=quiet)
    payload = gate(rows, allowlist)
    payload["config"] = {
        "op_baseline": os.path.abspath(op_baseline),
        "bench_baseline": os.path.abspath(bench_baseline),
        "backend": op_base.get("backend"),
        "tol_op": tol_op, "tol_bench": tol_bench,
        "tol_tuning": tol_tuning, "k": k,
        "allowlist": sorted(allowlist)}
    if out:
        with open(out, "w") as f:
            json.dump(payload, f, indent=1)
    return payload


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help=f"2-row op smoke {QUICK_OPS} with a loose "
                         f"(4x) tolerance — the CI/tier-1 invocation")
    ap.add_argument("--ops", default=None,
                    help="comma-separated op_bench rows")
    ap.add_argument("--bench", default=None,
                    help="comma-separated bench.py rows")
    ap.add_argument("--k", type=int, default=3,
                    help="fresh-side median-of-k (bench rows use "
                         "max(1, k//3))")
    ap.add_argument("--tol-op", type=float, default=2.0)
    ap.add_argument("--tol-bench", type=float, default=1.5)
    ap.add_argument("--tol-tuning", type=float, default=1.5)
    ap.add_argument("--tuning", default=None,
                    help="comma-separated tuning rows KERNEL:d/L/dtype"
                         " (default: the TUNING_ROWS set on full "
                         "runs; 'none' to skip)")
    ap.add_argument("--allow", default="",
                    help="comma-separated row names (op:NAME / "
                         "bench:NAME) that may regress without "
                         "failing the gate")
    ap.add_argument("--op-baseline", default=OP_BASELINE)
    ap.add_argument("--bench-baseline", default=BENCH_BASELINE)
    ap.add_argument("--out", default=GATE_OUT)
    ap.add_argument("--cpu", action="store_true",
                    help="pin to the CPU jax backend")
    args = ap.parse_args(argv)
    if args.cpu:
        import _cpu_debug  # noqa: F401

    if args.quick:
        op_names = list(QUICK_OPS)
        bench_names = []
        tuning_rows = []
        if args.tol_op == 2.0:
            # micro-second rows on a timeshared core need headroom;
            # the quick gate is a smoke of the MACHINERY, the full run
            # keeps the tight default
            args.tol_op = 4.0
    else:
        op_names = ([c[0] for c in _quick8()] + list(SPEC_OPS)
                    + list(LORA_OPS)
                    + list(RADIX_OPS)
                    + list(JOIN_OPS)) if args.ops is None else []
        bench_names = list(DEFAULT_BENCH) if args.bench is None else []
        tuning_rows = list(TUNING_ROWS)
    if args.ops is not None:
        op_names = [s for s in args.ops.split(",") if s]
    if args.bench is not None:
        bench_names = [s for s in args.bench.split(",") if s]
    if args.tuning is not None:
        tuning_rows = [] if args.tuning == "none" else [
            (s.split(":")[0], tuple(
                int(p) if p.isdigit() else p
                for p in s.split(":")[1].split("/")))
            for s in args.tuning.split(",") if s]

    payload = run_gate(
        op_names, bench_names, op_baseline=args.op_baseline,
        bench_baseline=args.bench_baseline, tol_op=args.tol_op,
        tol_bench=args.tol_bench, k=args.k,
        allowlist=[s for s in args.allow.split(",") if s],
        out=args.out, quiet=False, tuning_rows=tuning_rows,
        tol_tuning=args.tol_tuning)
    for r in payload["rows"]:
        print(f"{r['status']:>12}  {r['name']:<28} "
              f"baseline={r.get('baseline')} fresh={r.get('fresh')} "
              f"ratio={r.get('ratio')} tol={r['tol']}",
              file=sys.stderr)
    for name in payload["regressions"]:
        print(f"REGRESSION {name}", file=sys.stderr)
    for name in payload["missing"]:
        print(f"MISSING ROW {name}", file=sys.stderr)
    print(json.dumps({"ok": payload["ok"],
                      "regressions": payload["regressions"],
                      "missing": payload["missing"],
                      "out": args.out}))
    return 0 if payload["ok"] else 1


def _quick8():
    import op_bench

    return op_bench._configs()[:8]


if __name__ == "__main__":
    sys.exit(main())
