"""Per-op micro-benchmark harness over the lowering registry.

Reference role: operators/benchmark/op_tester.cc (config-driven per-op
timing) — TPU-native: each config builds a ONE-OP fluid program whose
inputs come from in-program random ops, then times it two ways:

  e2e_us   one Executor.run() call — dispatch + compile-cache hit path
  step_us  marginal per-step time inside an Executor.run_n lax.scan
           (the random feeder consumes the per-step rng key, so XLA
           cannot hoist the op out of the loop)

Usage:
  python tools/op_bench.py                 # full table -> OP_BENCH.json
  python tools/op_bench.py --quick         # first 8 configs
  python tools/op_bench.py --ops matmul,softmax
  python tools/op_bench.py --compare       # diff vs committed baseline,
                                           # exit 1 on >2x step_us regress

Runs on whatever jax backend the environment provides (CPU pin by
default under the test env; the real chip under the driver).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
BASELINE = os.path.join(REPO, "OP_BENCH.json")


def _f(shape, name, blk):
    """A float input fed by an in-program uniform_random."""
    v = blk.create_var(name=name)
    blk.append_op(type="uniform_random", inputs={},
                  outputs={"Out": [v.name]},
                  attrs={"shape": list(shape), "min": -1.0, "max": 1.0,
                         "dtype": "float32"})
    return v.name


def _i(shape, name, blk, high=1000):
    v = blk.create_var(name=name)
    blk.append_op(type="randint", inputs={}, outputs={"Out": [v.name]},
                  attrs={"shape": list(shape), "low": 0, "high": high})
    return v.name


def _p(shape, name, blk, scope):
    """A persistable parameter input (weights: constant across steps)."""
    import zlib

    v = blk.create_var(name=name, shape=list(shape), dtype="float32")
    v.persistable = True
    # crc32, not hash(): str hashing is salted per process and would
    # bench against different weight values every run
    rs = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    scope.set_value(name, (rs.randn(*shape) * 0.05).astype(np.float32))
    return v.name


# (name, builder(blk, scope) -> (op_type, inputs, outputs, attrs))
# shapes sized for ~ms-scale device work; the 30 hottest op families
# across the model zoo + optimizer/loss paths
def _configs():
    B, T, D, H = 32, 128, 768, 1024

    def simple(op, ins, outs, attrs=None):
        def build(blk, scope):
            return op, ins(blk, scope), outs, (attrs or {})
        return build

    cfgs = []

    def unary(op):
        return simple(op, lambda b, s: {"X": [_f((B, T, D), "x", b)]},
                      {"Out": 1})

    cfgs += [
        ("matmul", simple(
            "matmul", lambda b, s: {"X": [_f((B, T, D), "x", b)],
                                    "Y": [_p((D, D), "w", b, s)]},
            {"Out": 1})),
        ("mul", simple(
            "mul", lambda b, s: {"X": [_f((B * T, D), "x", b)],
                                 "Y": [_p((D, H), "w", b, s)]},
            {"Out": 1})),
        ("fc", simple(
            "fc", lambda b, s: {"Input": [_f((B * T, D), "x", b)],
                                "W": [_p((D, H), "w", b, s)],
                                "Bias": [_p((H,), "bias", b, s)]},
            {"Out": 1})),
        ("conv2d", simple(
            "conv2d", lambda b, s: {"Input": [_f((16, 64, 56, 56),
                                                 "x", b)],
                                    "Filter": [_p((64, 64, 3, 3),
                                                  "w", b, s)]},
            {"Output": 1},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1})),
        ("depthwise_conv2d", simple(
            "depthwise_conv2d",
            lambda b, s: {"Input": [_f((16, 64, 56, 56), "x", b)],
                          "Filter": [_p((64, 1, 3, 3), "w", b, s)]},
            {"Output": 1},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 64})),
        ("batch_norm", simple(
            "batch_norm",
            lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)],
                          "Scale": [_p((64,), "g", b, s)],
                          "Bias": [_p((64,), "bta", b, s)],
                          "Mean": [_p((64,), "mu", b, s)],
                          "Variance": [_p((64,), "va", b, s)]},
            {"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1,
             "SavedVariance": 1},
            {"is_test": False, "epsilon": 1e-5, "momentum": 0.9})),
        ("layer_norm", simple(
            "layer_norm",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Scale": [_p((D,), "g", b, s)],
                          "Bias": [_p((D,), "bta", b, s)]},
            {"Y": 1}, {"begin_norm_axis": 2})),
        ("softmax", unary("softmax")),
        ("relu", unary("relu")),
        ("gelu", unary("gelu")),
        ("tanh", unary("tanh")),
        ("sigmoid", unary("sigmoid")),
        ("exp", unary("exp")),
        ("dropout", simple(
            "dropout", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1, "Mask": 1},
            {"dropout_prob": 0.1,
             "dropout_implementation": "upscale_in_train"})),
        ("elementwise_add", simple(
            "elementwise_add",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Y": [_f((B, T, D), "y", b)]}, {"Out": 1})),
        ("elementwise_mul", simple(
            "elementwise_mul",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Y": [_f((B, T, D), "y", b)]}, {"Out": 1})),
        ("reduce_sum", simple(
            "reduce_sum", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"dim": [-1], "keep_dim": False})),
        ("reduce_mean", simple(
            "reduce_mean", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"dim": [-1], "keep_dim": False})),
        ("transpose2", simple(
            "transpose2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"axis": [0, 2, 1]})),
        ("reshape2", simple(
            "reshape2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"shape": [B * T, D]})),
        ("concat", simple(
            "concat", lambda b, s: {"X": [_f((B, T, D), "x", b),
                                          _f((B, T, D), "y", b)]},
            {"Out": 1}, {"axis": -1})),
        ("split", simple(
            "split", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 2}, {"num": 2, "axis": -1})),
        ("slice", simple(
            "slice", lambda b, s: {"Input": [_f((B, T, D), "x", b)]},
            {"Out": 1},
            {"axes": [1], "starts": [0], "ends": [T // 2]})),
        ("lookup_table_v2", simple(
            "lookup_table_v2",
            lambda b, s: {"Ids": [_i((B, T), "ids", b, high=30000)],
                          "W": [_p((30000, D), "emb", b, s)]},
            {"Out": 1})),
        ("gather", simple(
            "gather", lambda b, s: {"X": [_f((30000, D), "x", b)],
                                    "Index": [_i((4096,), "ids", b,
                                                 high=30000)]},
            {"Out": 1})),
        ("top_k_v2", simple(
            "top_k_v2", lambda b, s: {"X": [_f((B, 30000), "x", b)]},
            {"Out": 1, "Indices": 1}, {"k": 10, "axis": -1})),
        ("pool2d", simple(
            "pool2d", lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)]},
            {"Out": 1},
            {"pooling_type": "max", "ksize": [3, 3], "strides": [2, 2],
             "paddings": [1, 1]})),
        ("softmax_with_cross_entropy", simple(
            "softmax_with_cross_entropy",
            lambda b, s: {"Logits": [_f((B * T, D), "x", b)],
                          "Label": [_i((B * T, 1), "lbl", b, high=D)]},
            {"Softmax": 1, "Loss": 1}, {})),
        ("fused_sdpa", simple(
            "fused_sdpa",
            lambda b, s: {"Q": [_f((B, 12, T, 64), "q", b)],
                          "K": [_f((B, 12, T, 64), "k", b)],
                          "V": [_f((B, 12, T, 64), "v", b)]},
            {"Out": 1}, {"scale": 0.125})),
        ("scale", simple(
            "scale", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"scale": 1.5, "bias": 0.1})),
        ("sqrt", unary("sqrt")),
        ("cast", simple(
            "cast", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"in_dtype": "float32", "out_dtype": "float16"})),
    ]
    return cfgs


def bench_one(name, builder, steps=30):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.unique_name.guard(), fluid.program_guard(main,
                                                            startup):
            blk = main.global_block()
            op, ins, outs, attrs = builder(blk, scope)
            out_map = {}
            for slot, n_out in outs.items():
                out_map[slot] = [
                    blk.create_var(name=f"ob_{slot}_{i}").name
                    for i in range(n_out)]
            blk.append_op(type=op, inputs=ins, outputs=out_map,
                          attrs=attrs)
            # persistable accumulator consuming the op output: without
            # it the scan carry ignores the op and XLA dead-code
            # eliminates every step but the unrolled last one
            first_out = out_map[next(iter(out_map))][0]
            red = blk.create_var(name="ob_red")
            blk.append_op(type="reduce_sum",
                          inputs={"X": [first_out]},
                          outputs={"Out": [red.name]},
                          attrs={"dim": [], "reduce_all": True,
                                 "keep_dim": False})
            cst = blk.create_var(name="ob_cst")
            blk.append_op(type="cast", inputs={"X": [red]},
                          outputs={"Out": [cst.name]},
                          attrs={"in_dtype": "float32",
                                 "out_dtype": "float32"})
            acc = blk.create_var(name="ob_acc", shape=[1],
                                 dtype="float32")
            acc.persistable = True
            blk.append_op(type="elementwise_add",
                          inputs={"X": ["ob_acc"], "Y": [cst]},
                          outputs={"Out": ["ob_acc"]}, attrs={})
        scope.set_value("ob_acc", np.zeros(1, np.float32))
        exe = fluid.Executor()
        exe.run(startup)
        fetch = ["ob_acc"]

        t0 = time.perf_counter()
        exe.run(main, {}, fetch)          # compile
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        exe.run(main, {}, fetch)
        e2e_s = time.perf_counter() - t0

        for n in (steps, 5):                  # compile both scan lengths
            exe.run_n(main, {}, fetch, n=n)
        slopes = []
        for _ in range(5):                    # median of adjacent pairs
            t0 = time.perf_counter()
            exe.run_n(main, {}, fetch, n=5)
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            exe.run_n(main, {}, fetch, n=steps)
            t_hi = time.perf_counter() - t0
            if t_hi > t_lo:
                slopes.append((t_hi - t_lo) / (steps - 5))
        slopes.sort()
        dt = slopes[len(slopes) // 2] if slopes else 0.0
    return {"e2e_us": round(e2e_s * 1e6, 1),
            "step_us": round(dt * 1e6, 2),
            "compile_s": round(compile_s, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="pin to the virtual-CPU jax backend (the axon "
                         "site hook otherwise grabs the tunnel chip)")
    ap.add_argument("--quick", action="store_true",
                    help="first 8 configs only")
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--out", default=BASELINE)
    ap.add_argument("--compare", action="store_true",
                    help="compare against the committed baseline; exit 1 "
                         "when any op's step_us regressed >2x")
    args = ap.parse_args()
    if args.cpu:
        sys.path.insert(0, REPO)
        import _cpu_debug  # noqa: F401  (forces the cpu backend)

    cfgs = _configs()
    if args.ops:
        want = set(args.ops.split(","))
        cfgs = [c for c in cfgs if c[0] in want]
    elif args.quick:
        cfgs = cfgs[:8]

    results = {}
    for name, builder in cfgs:
        try:
            results[name] = bench_one(name, builder)
        except Exception as e:  # record, keep the table alive
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        r = results[name]
        print(f"{name:28s} {json.dumps(r)}", file=sys.stderr)

    import jax

    record = {"backend": jax.default_backend(),
              "ops": results}
    if args.compare:
        try:
            with open(BASELINE) as f:
                base = json.load(f)
        except Exception:
            print("no baseline to compare against", file=sys.stderr)
            base = None
        bad = []
        if base and base.get("backend") == record["backend"]:
            for op, r in results.items():
                b = base["ops"].get(op, {})
                if "step_us" in r and "step_us" in b and \
                        b["step_us"] > 0 and \
                        r["step_us"] > 2.0 * b["step_us"]:
                    bad.append((op, b["step_us"], r["step_us"]))
        for op, old, new in bad:
            print(f"REGRESSION {op}: {old}us -> {new}us", file=sys.stderr)
        print(json.dumps({"regressions": len(bad)}))
        sys.exit(1 if bad else 0)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
    print(json.dumps({"ops_benchmarked": len(results),
                      "out": args.out}))


if __name__ == "__main__":
    main()
