"""Per-op micro-benchmark harness over the lowering registry.

Reference role: operators/benchmark/op_tester.cc (config-driven per-op
timing) — TPU-native: each config builds a ONE-OP fluid program whose
inputs come from in-program random ops, then times it two ways:

  e2e_us   one Executor.run() call — dispatch + compile-cache hit path
  step_us  marginal per-step time inside an Executor.run_n lax.scan
           (the random feeder consumes the per-step rng key, so XLA
           cannot hoist the op out of the loop)

`*_bwd` configs time the op's forward PLUS its backward: the scalar
reduction of the op output is differentiated w.r.t. the hot input
slots via fluid.gradients (the jax_autodiff op), and every gradient
feeds the persistable accumulator so neither pass can be DCE'd out of
the scan — the CI gate watches training-path regressions, not just
inference (VERDICT weak #4).

Usage:
  python tools/op_bench.py                 # full table -> OP_BENCH.json
  python tools/op_bench.py --quick         # first 8 configs
  python tools/op_bench.py --ops matmul,softmax
  python tools/op_bench.py --compare       # diff vs committed baseline,
                                           # exit 1 on >2x step_us regress

Runs on whatever jax backend the environment provides (CPU pin by
default under the test env; the real chip under the driver).
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
BASELINE = os.path.join(REPO, "OP_BENCH.json")


def _f(shape, name, blk):
    """A float input fed by an in-program uniform_random."""
    v = blk.create_var(name=name)
    blk.append_op(type="uniform_random", inputs={},
                  outputs={"Out": [v.name]},
                  attrs={"shape": list(shape), "min": -1.0, "max": 1.0,
                         "dtype": "float32"})
    return v.name


def _i(shape, name, blk, high=1000):
    v = blk.create_var(name=name)
    blk.append_op(type="randint", inputs={}, outputs={"Out": [v.name]},
                  attrs={"shape": list(shape), "low": 0, "high": high})
    return v.name


def _p(shape, name, blk, scope):
    """A persistable parameter input (weights: constant across steps)."""
    import zlib

    v = blk.create_var(name=name, shape=list(shape), dtype="float32")
    v.persistable = True
    # crc32, not hash(): str hashing is salted per process and would
    # bench against different weight values every run
    rs = np.random.RandomState(zlib.crc32(name.encode()) % (2 ** 31))
    scope.set_value(name, (rs.randn(*shape) * 0.05).astype(np.float32))
    return v.name


# (name, builder(blk, scope) -> (op_type, inputs, outputs, attrs))
# shapes sized for ~ms-scale device work; the 30 hottest op families
# across the model zoo + optimizer/loss paths
def _configs():
    B, T, D, H = 32, 128, 768, 1024

    def simple(op, ins, outs, attrs=None):
        def build(blk, scope):
            return op, ins(blk, scope), outs, (attrs or {})
        return build

    cfgs = []

    def unary(op):
        return simple(op, lambda b, s: {"X": [_f((B, T, D), "x", b)]},
                      {"Out": 1})

    cfgs += [
        ("matmul", simple(
            "matmul", lambda b, s: {"X": [_f((B, T, D), "x", b)],
                                    "Y": [_p((D, D), "w", b, s)]},
            {"Out": 1})),
        ("mul", simple(
            "mul", lambda b, s: {"X": [_f((B * T, D), "x", b)],
                                 "Y": [_p((D, H), "w", b, s)]},
            {"Out": 1})),
        ("fc", simple(
            "fc", lambda b, s: {"Input": [_f((B * T, D), "x", b)],
                                "W": [_p((D, H), "w", b, s)],
                                "Bias": [_p((H,), "bias", b, s)]},
            {"Out": 1})),
        ("conv2d", simple(
            "conv2d", lambda b, s: {"Input": [_f((16, 64, 56, 56),
                                                 "x", b)],
                                    "Filter": [_p((64, 64, 3, 3),
                                                  "w", b, s)]},
            {"Output": 1},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1})),
        ("depthwise_conv2d", simple(
            "depthwise_conv2d",
            lambda b, s: {"Input": [_f((16, 64, 56, 56), "x", b)],
                          "Filter": [_p((64, 1, 3, 3), "w", b, s)]},
            {"Output": 1},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 64})),
        ("batch_norm", simple(
            "batch_norm",
            lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)],
                          "Scale": [_p((64,), "g", b, s)],
                          "Bias": [_p((64,), "bta", b, s)],
                          "Mean": [_p((64,), "mu", b, s)],
                          "Variance": [_p((64,), "va", b, s)]},
            {"Y": 1, "MeanOut": 1, "VarianceOut": 1, "SavedMean": 1,
             "SavedVariance": 1},
            {"is_test": False, "epsilon": 1e-5, "momentum": 0.9})),
        ("layer_norm", simple(
            "layer_norm",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Scale": [_p((D,), "g", b, s)],
                          "Bias": [_p((D,), "bta", b, s)]},
            {"Y": 1}, {"begin_norm_axis": 2})),
        ("softmax", unary("softmax")),
        ("relu", unary("relu")),
        ("gelu", unary("gelu")),
        ("tanh", unary("tanh")),
        ("sigmoid", unary("sigmoid")),
        ("exp", unary("exp")),
        ("dropout", simple(
            "dropout", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1, "Mask": 1},
            {"dropout_prob": 0.1,
             "dropout_implementation": "upscale_in_train"})),
        ("elementwise_add", simple(
            "elementwise_add",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Y": [_f((B, T, D), "y", b)]}, {"Out": 1})),
        ("elementwise_mul", simple(
            "elementwise_mul",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Y": [_f((B, T, D), "y", b)]}, {"Out": 1})),
        ("reduce_sum", simple(
            "reduce_sum", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"dim": [-1], "keep_dim": False})),
        ("reduce_mean", simple(
            "reduce_mean", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"dim": [-1], "keep_dim": False})),
        ("transpose2", simple(
            "transpose2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"axis": [0, 2, 1]})),
        ("reshape2", simple(
            "reshape2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"shape": [B * T, D]})),
        ("concat", simple(
            "concat", lambda b, s: {"X": [_f((B, T, D), "x", b),
                                          _f((B, T, D), "y", b)]},
            {"Out": 1}, {"axis": -1})),
        ("split", simple(
            "split", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 2}, {"num": 2, "axis": -1})),
        ("slice", simple(
            "slice", lambda b, s: {"Input": [_f((B, T, D), "x", b)]},
            {"Out": 1},
            {"axes": [1], "starts": [0], "ends": [T // 2]})),
        ("lookup_table_v2", simple(
            "lookup_table_v2",
            lambda b, s: {"Ids": [_i((B, T), "ids", b, high=30000)],
                          "W": [_p((30000, D), "emb", b, s)]},
            {"Out": 1})),
        ("gather", simple(
            "gather", lambda b, s: {"X": [_f((30000, D), "x", b)],
                                    "Index": [_i((4096,), "ids", b,
                                                 high=30000)]},
            {"Out": 1})),
        ("top_k_v2", simple(
            "top_k_v2", lambda b, s: {"X": [_f((B, 30000), "x", b)]},
            {"Out": 1, "Indices": 1}, {"k": 10, "axis": -1})),
        ("pool2d", simple(
            "pool2d", lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)]},
            {"Out": 1},
            {"pooling_type": "max", "ksize": [3, 3], "strides": [2, 2],
             "paddings": [1, 1]})),
        ("softmax_with_cross_entropy", simple(
            "softmax_with_cross_entropy",
            lambda b, s: {"Logits": [_f((B * T, D), "x", b)],
                          "Label": [_i((B * T, 1), "lbl", b, high=D)]},
            {"Softmax": 1, "Loss": 1}, {})),
        ("fused_sdpa", simple(
            "fused_sdpa",
            lambda b, s: {"Q": [_f((B, 12, T, 64), "q", b)],
                          "K": [_f((B, 12, T, 64), "k", b)],
                          "V": [_f((B, 12, T, 64), "v", b)]},
            {"Out": 1}, {"scale": 0.125})),
        ("scale", simple(
            "scale", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"scale": 1.5, "bias": 0.1})),
        ("sqrt", unary("sqrt")),
        ("cast", simple(
            "cast", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"in_dtype": "float32", "out_dtype": "float16"})),
    ]
    cfgs += _configs_extended(simple, unary)
    cfgs += _configs_bwd(cfgs)
    cfgs += _configs_optimizer()
    cfgs += _configs_flash_decode()
    cfgs += _configs_serving()
    cfgs += _configs_spec_decode()
    cfgs += _configs_paged_decode()
    cfgs += _configs_paged_verify()
    cfgs += _configs_sharded_decode()
    cfgs += _configs_lora_int8()
    cfgs += _configs_prefix_attach()
    cfgs += _configs_join_donation()
    return cfgs


def _configs_extended(simple, unary):
    """r05 widening (VERDICT r04 weak #6): cover the sequence /
    embedding / fused-CTR / detection / RNN families the bench models
    actually execute, so the CI regression gate watches the hot paths
    — reference op_tester.cc configs role. Sequence ops get an
    in-program int32 lengths companion (name + @@LOD) so the MASKED
    kernel path is what's timed, not the dense fallback."""
    B, T, D, H = 32, 128, 768, 1024
    SB, ST, SD = 64, 50, 64           # sequence family shapes (CTR-ish)

    def _lens(b, name, t=ST, n=SB):
        v = b.create_var(name=name + "@@LOD")
        b.append_op(type="randint", inputs={},
                    outputs={"Out": [v.name]},
                    attrs={"shape": [n], "low": 1, "high": t + 1,
                           "dtype": "int32"})
        return v

    def seq(op, outs=None, attrs=None, extra=None):
        def build(blk, scope):
            x = _f((SB, ST, SD), "x", blk)
            _lens(blk, "x")
            ins = {"X": [x]}
            if extra:
                ins.update(extra(blk, scope))
            return op, ins, (outs or {"Out": 1}), (attrs or {})
        return build

    def ew(op):
        return simple(op, lambda b, s: {"X": [_f((B, T, D), "x", b)],
                                        "Y": [_f((B, T, D), "y", b)]},
                      {"Out": 1})

    cfgs = [
        # ---- sequence family (CTR/NLP hot path) ----
        ("sequence_pool", seq("sequence_pool", {"Out": 1, "MaxIndex": 1},
                              {"pooltype": "SUM"})),
        ("sequence_pool_max", seq("sequence_pool",
                                  {"Out": 1, "MaxIndex": 1},
                                  {"pooltype": "MAX"})),
        ("sequence_softmax", seq("sequence_softmax")),
        ("sequence_reverse", seq("sequence_reverse", {"Y": 1})),
        ("sequence_conv", seq(
            "sequence_conv", {"Out": 1},
            {"contextLength": 3, "contextStart": -1, "contextStride": 1},
            extra=lambda b, s: {"Filter": [_p((3 * SD, SD), "scw", b,
                                              s)]})),
        ("im2sequence", simple(
            "im2sequence",
            lambda b, s: {"X": [_f((8, 16, 28, 28), "x", b)]},
            {"Out": 1},
            {"kernels": [3, 3], "strides": [1, 1],
             "paddings": [0, 0, 0, 0]})),
        # ---- fused CTR / NLP ops ----
        ("fusion_gru", seq(
            "fusion_gru", {"Hidden": 1, "XX": 1},
            {"activation": "tanh", "gate_activation": "sigmoid",
             "is_reverse": False},
            extra=lambda b, s: {"WeightX": [_p((SD, 3 * SD), "wx", b, s)],
                                "WeightH": [_p((SD, 3 * SD), "wh", b, s)],
                                "Bias": [_p((1, 3 * SD), "bg", b, s)]})),
        ("fusion_lstm", seq(
            "fusion_lstm", {"Hidden": 1, "Cell": 1, "XX": 1},
            {"candidate_activation": "tanh", "gate_activation": "sigmoid",
             "cell_activation": "tanh", "is_reverse": False},
            extra=lambda b, s: {"WeightX": [_p((SD, 4 * SD), "wx", b, s)],
                                "WeightH": [_p((SD, 4 * SD), "wh", b, s)],
                                "Bias": [_p((1, 4 * SD), "bg", b, s)]})),
        ("attention_lstm", seq(
            "attention_lstm",
            {"Hidden": 1, "Cell": 1, "AttentionedX": 1},
            {"gate_activation": "sigmoid", "cell_activation": "tanh",
             "candidate_activation": "tanh"},
            extra=lambda b, s: {
                "AttentionWeight": [_p((SD + SD, 1), "aw", b, s)],
                "AttentionBias": [_p((1,), "ab", b, s)],
                "LSTMWeight": [_p((SD + SD, 4 * SD), "lw", b, s)],
                "LSTMBias": [_p((1, 4 * SD), "lb", b, s)]})),
        ("multihead_matmul", simple(
            "multihead_matmul",
            lambda b, s: {"Input": [_f((B, T, D), "x", b)],
                          "W": [_p((D, 3 * D), "qkvw", b, s)],
                          "Bias": [_p((3 * D,), "qkvb", b, s)]},
            {"Out": 1}, {"head_number": 12,
                         "alpha": 1.0 / 8.0})),
        ("skip_layernorm", simple(
            "skip_layernorm",
            lambda b, s: {"X": [_f((B, T, D), "x", b)],
                          "Y": [_f((B, T, D), "y", b)],
                          "Scale": [_p((D,), "g", b, s)],
                          "Bias": [_p((D,), "bt", b, s)]},
            {"Out": 1}, {"epsilon": 1e-5})),
        ("fused_fc_elementwise_layernorm", simple(
            "fused_fc_elementwise_layernorm",
            lambda b, s: {"X": [_f((B * T, D), "x", b)],
                          "W": [_p((D, D), "w", b, s)],
                          "Y": [_f((B * T, D), "y", b)],
                          "Scale": [_p((D,), "g", b, s)],
                          "Bias1": [_p((D,), "b1", b, s)]},
            {"Out": 1}, {"epsilon": 1e-5, "begin_norm_axis": 1})),
        # ---- RNN (unfused reference forms): the lengths companion
        # rides on the op's ACTUAL sequence input slot (Input/"xg") so
        # the masked recurrence is what gets timed ----
        ("lstm", _rnn_cfg("lstm", 4, SB, ST, SD,
                          {"Hidden": 1, "Cell": 1, "BatchGate": 1,
                           "BatchCellPreAct": 1},
                          {"use_peepholes": False,
                           "gate_activation": "sigmoid",
                           "cell_activation": "tanh",
                           "candidate_activation": "tanh"})),
        ("gru", _rnn_cfg("gru", 3, SB, ST, SD,
                         {"Hidden": 1, "BatchGate": 1,
                          "BatchResetHiddenPrev": 1},
                         {"activation": "tanh",
                          "gate_activation": "sigmoid",
                          "is_reverse": False})),
        # ---- conv / vision family ----
        ("conv2d_1x1", simple(
            "conv2d", lambda b, s: {"Input": [_f((16, 256, 56, 56),
                                                 "x", b)],
                                    "Filter": [_p((64, 256, 1, 1),
                                                  "w", b, s)]},
            {"Output": 1},
            {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
             "groups": 1})),
        ("conv2d_s2", simple(
            "conv2d", lambda b, s: {"Input": [_f((16, 128, 56, 56),
                                                 "x", b)],
                                    "Filter": [_p((128, 128, 3, 3),
                                                  "w", b, s)]},
            {"Output": 1},
            {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1],
             "groups": 1})),
        ("conv2d_transpose", simple(
            "conv2d_transpose",
            lambda b, s: {"Input": [_f((8, 128, 28, 28), "x", b)],
                          "Filter": [_p((128, 64, 2, 2), "w", b, s)]},
            {"Output": 1},
            {"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1],
             "groups": 1})),
        ("pool2d_avg", simple(
            "pool2d", lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)]},
            {"Out": 1},
            {"pooling_type": "avg", "ksize": [3, 3], "strides": [2, 2],
             "paddings": [1, 1]})),
        ("pool2d_global", simple(
            "pool2d", lambda b, s: {"X": [_f((16, 2048, 7, 7), "x", b)]},
            {"Out": 1},
            {"pooling_type": "avg", "ksize": [1, 1],
             "global_pooling": True})),
        ("bilinear_interp_v2", simple(
            "bilinear_interp_v2",
            lambda b, s: {"X": [_f((8, 64, 28, 28), "x", b)]},
            {"Out": 1},
            {"out_h": 56, "out_w": 56, "interp_method": "bilinear",
             "align_corners": False, "data_layout": "NCHW"})),
        ("nearest_interp_v2", simple(
            "nearest_interp_v2",
            lambda b, s: {"X": [_f((8, 64, 28, 28), "x", b)]},
            {"Out": 1},
            {"out_h": 56, "out_w": 56, "interp_method": "nearest",
             "align_corners": False, "data_layout": "NCHW"})),
        ("grid_sampler", simple(
            "grid_sampler",
            lambda b, s: {"X": [_f((8, 32, 28, 28), "x", b)],
                          "Grid": [_f((8, 28, 28, 2), "g", b)]},
            {"Output": 1}, {"mode": "bilinear",
                            "padding_mode": "zeros",
                            "align_corners": True})),
        ("affine_channel", simple(
            "affine_channel",
            lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)],
                          "Scale": [_p((64,), "g", b, s)],
                          "Bias": [_p((64,), "bt", b, s)]},
            {"Out": 1}, {"data_layout": "NCHW"})),
        ("pixel_shuffle", simple(
            "pixel_shuffle",
            lambda b, s: {"X": [_f((8, 64, 28, 28), "x", b)]},
            {"Out": 1}, {"upscale_factor": 2})),
        ("shuffle_channel", simple(
            "shuffle_channel",
            lambda b, s: {"X": [_f((8, 64, 28, 28), "x", b)]},
            {"Out": 1}, {"group": 4})),
        ("pad2d", simple(
            "pad2d", lambda b, s: {"X": [_f((16, 64, 56, 56), "x", b)]},
            {"Out": 1}, {"paddings": [1, 1, 1, 1], "mode": "constant",
                         "pad_value": 0.0, "data_format": "NCHW"})),
        ("instance_norm", simple(
            "instance_norm",
            lambda b, s: {"X": [_f((16, 64, 28, 28), "x", b)],
                          "Scale": [_p((64,), "g", b, s)],
                          "Bias": [_p((64,), "bt", b, s)]},
            {"Y": 1, "SavedMean": 1, "SavedVariance": 1},
            {"epsilon": 1e-5})),
        ("group_norm", simple(
            "group_norm",
            lambda b, s: {"X": [_f((16, 64, 28, 28), "x", b)],
                          "Scale": [_p((64,), "g", b, s)],
                          "Bias": [_p((64,), "bt", b, s)]},
            {"Y": 1, "Mean": 1, "Variance": 1},
            {"epsilon": 1e-5, "groups": 8})),
        # ---- detection family ----
        ("prior_box", simple(
            "prior_box",
            lambda b, s: {"Input": [_f((8, 64, 28, 28), "x", b)],
                          "Image": [_f((8, 3, 224, 224), "img", b)]},
            {"Boxes": 1, "Variances": 1},
            {"min_sizes": [32.0], "max_sizes": [64.0],
             "aspect_ratios": [1.0, 2.0], "flip": True, "clip": True,
             "variances": [0.1, 0.1, 0.2, 0.2], "step_w": 0.0,
             "step_h": 0.0, "offset": 0.5})),
        ("box_coder", simple(
            "box_coder",
            lambda b, s: {"PriorBox": [_f((4096, 4), "pb", b)],
                          "TargetBox": [_f((4096, 4), "tb", b)]},
            {"OutputBox": 1},
            {"code_type": "decode_center_size", "box_normalized": True,
             "variance": [0.1, 0.1, 0.2, 0.2]})),
        ("iou_similarity", simple(
            "iou_similarity",
            lambda b, s: {"X": [_f((1024, 4), "x", b)],
                          "Y": [_f((256, 4), "y", b)]},
            {"Out": 1}, {"box_normalized": True})),
        # ---- losses ----
        ("sigmoid_cross_entropy_with_logits", simple(
            "sigmoid_cross_entropy_with_logits",
            lambda b, s: {"X": [_f((B * T, 80), "x", b)],
                          "Label": [_f((B * T, 80), "lbl", b)]},
            {"Out": 1}, {"normalize": False})),
        ("smooth_l1_loss", simple(
            "smooth_l1_loss",
            lambda b, s: {"X": [_f((4096, 4), "x", b)],
                          "Y": [_f((4096, 4), "y", b)]},
            {"Out": 1, "Diff": 1}, {"sigma": 1.0})),
        ("huber_loss", simple(
            "huber_loss",
            lambda b, s: {"X": [_f((4096, 1), "x", b)],
                          "Y": [_f((4096, 1), "y", b)]},
            {"Out": 1, "Residual": 1}, {"delta": 1.0})),
        ("bce_loss", simple(
            "bce_loss",
            lambda b, s: {"X": [_sig01(b, (B * T, 1), "x")],
                          "Label": [_sig01(b, (B * T, 1), "lbl")]},
            {"Out": 1})),
        ("kldiv_loss", simple(
            "kldiv_loss",
            lambda b, s: {"X": [_f((B, T), "x", b)],
                          "Target": [_sig01(b, (B, T), "t")]},
            {"Loss": 1}, {"reduction": "mean"})),
        ("log_softmax", simple(
            "log_softmax", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"axis": -1})),
        ("cross_entropy", simple(
            "cross_entropy",
            lambda b, s: {"X": [_softmaxed(b, (B * T, 128), "x")],
                          "Label": [_i((B * T, 1), "lbl", b, high=128)]},
            {"Y": 1}, {"soft_label": False})),
        ("label_smooth", simple(
            "label_smooth",
            lambda b, s: {"X": [_sig01(b, (B * T, 128), "x")]},
            {"Out": 1}, {"epsilon": 0.1})),
        ("squared_l2_norm", simple(
            "squared_l2_norm",
            lambda b, s: {"X": [_f((B * T, D), "x", b)]}, {"Out": 1})),
        # ---- elementwise / math breadth ----
        ("elementwise_sub", ew("elementwise_sub")),
        ("elementwise_div", ew("elementwise_div")),
        ("elementwise_max", ew("elementwise_max")),
        ("elementwise_min", ew("elementwise_min")),
        ("elementwise_pow", simple(
            "elementwise_pow",
            lambda b, s: {"X": [_sig01(b, (B, T, D), "x")],
                          "Y": [_sig01(b, (B, T, D), "y")]}, {"Out": 1})),
        ("clip", simple(
            "clip", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"min": -0.5, "max": 0.5})),
        ("abs", unary("abs")),
        ("log", simple(
            "log", lambda b, s: {"X": [_sig01(b, (B, T, D), "x")]},
            {"Out": 1})),
        ("rsqrt", simple(
            "rsqrt", lambda b, s: {"X": [_sig01(b, (B, T, D), "x")]},
            {"Out": 1})),
        ("square", unary("square")),
        ("floor", unary("floor")),
        ("softplus", unary("softplus")),
        ("softsign", unary("softsign")),
        ("leaky_relu", simple(
            "leaky_relu", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"alpha": 0.1})),
        ("relu6", unary("relu6")),
        ("hard_swish", unary("hard_swish")),
        ("hard_sigmoid", unary("hard_sigmoid")),
        ("swish", simple(
            "swish", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"beta": 1.0})),
        ("mish", unary("mish")),
        ("elu", unary("elu")),
        ("sign", unary("sign")),
        ("mean", simple(
            "mean", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1})),
        ("cumsum", simple(
            "cumsum", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1}, {"axis": -1})),
        ("sum3", simple(
            "sum", lambda b, s: {"X": [_f((B, T, D), "x", b),
                                       _f((B, T, D), "y", b),
                                       _f((B, T, D), "z", b)]},
            {"Out": 1})),
        # ---- shape / indexing breadth ----
        ("matmul_v2", simple(
            "matmul_v2", lambda b, s: {"X": [_f((B, T, D), "x", b)],
                                       "Y": [_p((D, D), "w", b, s)]},
            {"Out": 1}, {"trans_x": False, "trans_y": False})),
        ("bmm", simple(
            "bmm", lambda b, s: {"X": [_f((B * 12, T, 64), "x", b)],
                                 "Y": [_f((B * 12, 64, T), "y", b)]},
            {"Out": 1})),
        ("stack", simple(
            "stack", lambda b, s: {"X": [_f((B, T), "x", b),
                                        _f((B, T), "y", b),
                                        _f((B, T), "z", b)]},
            {"Y": 1}, {"axis": 0})),
        ("tile", simple(
            "tile", lambda b, s: {"X": [_f((B, T), "x", b)]},
            {"Out": 1}, {"repeat_times": [1, 4]})),
        ("expand_v2", simple(
            "expand_v2", lambda b, s: {"X": [_f((B, 1, D), "x", b)]},
            {"Out": 1}, {"shape": [B, T, D]})),
        ("flatten2", simple(
            "flatten2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1, "XShape": 1}, {"axis": 2})),
        ("squeeze2", simple(
            "squeeze2", lambda b, s: {"X": [_f((B, 1, T, D), "x", b)]},
            {"Out": 1, "XShape": 1}, {"axes": [1]})),
        ("unsqueeze2", simple(
            "unsqueeze2", lambda b, s: {"X": [_f((B, T, D), "x", b)]},
            {"Out": 1, "XShape": 1}, {"axes": [1]})),
        ("strided_slice", simple(
            "strided_slice",
            lambda b, s: {"Input": [_f((B, T, D), "x", b)]},
            {"Out": 1},
            {"axes": [1], "starts": [0], "ends": [T], "strides": [2]})),
        ("gather_nd", simple(
            "gather_nd",
            lambda b, s: {"X": [_f((512, 512), "x", b)],
                          "Index": [_i((4096, 2), "ids", b, high=512)]},
            {"Out": 1})),
        ("scatter", simple(
            "scatter",
            lambda b, s: {"X": [_f((30000, 64), "x", b)],
                          "Ids": [_i((4096,), "ids", b, high=30000)],
                          "Updates": [_f((4096, 64), "u", b)]},
            {"Out": 1}, {"overwrite": False})),
        ("scatter_nd_add", simple(
            "scatter_nd_add",
            lambda b, s: {"X": [_f((512, 512), "x", b)],
                          "Index": [_i((4096, 2), "ids", b, high=512)],
                          "Updates": [_f((4096,), "u", b)]},
            {"Out": 1})),
        ("index_select", simple(
            "index_select",
            lambda b, s: {"X": [_f((30000, 64), "x", b)],
                          "Index": [_i((4096,), "ids", b, high=30000)]},
            {"Out": 1}, {"dim": 0})),
        ("one_hot_v2", simple(
            "one_hot_v2",
            lambda b, s: {"X": [_i((B * T,), "ids", b, high=128)]},
            {"Out": 1}, {"depth": 128})),
        ("lookup_table", simple(
            "lookup_table",
            lambda b, s: {"Ids": [_i((B * T, 1), "ids", b, high=30000)],
                          "W": [_p((30000, D), "emb", b, s)]},
            {"Out": 1})),
        ("arg_max", simple(
            "arg_max", lambda b, s: {"X": [_f((B, 30000), "x", b)]},
            {"Out": 1}, {"axis": -1})),
        ("argsort", simple(
            "argsort", lambda b, s: {"X": [_f((B, 4096), "x", b)]},
            {"Out": 1, "Indices": 1}, {"axis": -1})),
    ]
    cfgs += _configs_special()
    return cfgs


def _bwd(builder, *slots):
    """Wrap a forward builder into a fwd+bwd config: the 5th tuple slot
    names the input slots to differentiate; bench_one appends a
    fluid.gradients (jax_autodiff) op over the scalar reduction of the
    op's first output and accumulates every gradient, so the scan times
    the full forward + backward of the op."""
    def build(blk, scope):
        op, ins, outs, attrs = builder(blk, scope)
        return op, ins, outs, attrs, list(slots)
    return build


# (forward config name, input slots to differentiate) — the hot
# families first (attention / matmul / embedding / norm), then
# activation, loss, elementwise and indexing breadth: the CI perf gate
# (scripts/ci.sh --compare) was forward-only before (VERDICT weak #4)
_BWD_FAMILIES = [
    # attention + matmul family
    ("fused_sdpa", ["Q", "K", "V"]),
    ("multihead_matmul", ["Input"]),
    ("matmul", ["X"]), ("matmul_v2", ["X"]), ("mul", ["X"]),
    ("fc", ["Input"]), ("bmm", ["X", "Y"]),
    # embedding family (grads w.r.t. the table, the trained operand)
    ("lookup_table_v2", ["W"]), ("lookup_table", ["W"]),
    ("gather", ["X"]), ("gather_nd", ["X"]), ("index_select", ["X"]),
    # norms
    ("layer_norm", ["X"]), ("batch_norm", ["X"]),
    ("instance_norm", ["X"]), ("group_norm", ["X"]),
    ("skip_layernorm", ["X"]),
    ("fused_fc_elementwise_layernorm", ["X"]),
    # activations
    ("softmax", ["X"]), ("log_softmax", ["X"]), ("relu", ["X"]),
    ("gelu", ["X"]), ("tanh", ["X"]), ("sigmoid", ["X"]),
    ("leaky_relu", ["X"]), ("swish", ["X"]), ("dropout", ["X"]),
    # losses
    ("softmax_with_cross_entropy", ["Logits"]),
    ("sigmoid_cross_entropy_with_logits", ["X"]),
    ("smooth_l1_loss", ["X"]), ("huber_loss", ["X"]),
    ("bce_loss", ["X"]), ("kldiv_loss", ["X"]),
    ("squared_l2_norm", ["X"]),
    # elementwise / reduction / shape breadth
    ("elementwise_add", ["X", "Y"]), ("elementwise_mul", ["X", "Y"]),
    ("elementwise_sub", ["X"]), ("elementwise_div", ["X"]),
    ("reduce_sum", ["X"]), ("reduce_mean", ["X"]), ("mean", ["X"]),
    ("cumsum", ["X"]), ("sum3", ["X"]), ("scale", ["X"]),
    ("transpose2", ["X"]), ("reshape2", ["X"]), ("concat", ["X"]),
    ("split", ["X"]), ("slice", ["Input"]),
    ("pool2d", ["X"]), ("pool2d_avg", ["X"]),
    ("tile", ["X"]), ("expand_v2", ["X"]), ("stack", ["X"]),
]


def _conv_bwd_cfgs(simple):
    """Conv-family backward configs get DEDICATED, smaller shapes: the
    forward conv configs run seconds-per-step on the CPU gate machine
    and a backward pass multiplies that ~3x — same op lowering, same
    regression signal, tractable wall-clock."""
    def c(name, op, ins, outs, attrs, slots):
        return (f"{name}_bwd", _bwd(simple(op, ins, outs, attrs),
                                    *slots))
    return [
        c("conv2d", "conv2d",
          lambda b, s: {"Input": [_f((4, 32, 28, 28), "x", b)],
                        "Filter": [_p((32, 32, 3, 3), "w", b, s)]},
          {"Output": 1},
          {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
           "groups": 1}, ["Input", "Filter"]),
        c("conv2d_1x1", "conv2d",
          lambda b, s: {"Input": [_f((4, 128, 28, 28), "x", b)],
                        "Filter": [_p((32, 128, 1, 1), "w", b, s)]},
          {"Output": 1},
          {"strides": [1, 1], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1}, ["Input"]),
        c("conv2d_s2", "conv2d",
          lambda b, s: {"Input": [_f((4, 64, 28, 28), "x", b)],
                        "Filter": [_p((64, 64, 3, 3), "w", b, s)]},
          {"Output": 1},
          {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1],
           "groups": 1}, ["Input"]),
        c("depthwise_conv2d", "depthwise_conv2d",
          lambda b, s: {"Input": [_f((4, 32, 28, 28), "x", b)],
                        "Filter": [_p((32, 1, 3, 3), "w", b, s)]},
          {"Output": 1},
          {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1],
           "groups": 32}, ["Input"]),
        c("conv2d_transpose", "conv2d_transpose",
          lambda b, s: {"Input": [_f((4, 64, 14, 14), "x", b)],
                        "Filter": [_p((64, 32, 2, 2), "w", b, s)]},
          {"Output": 1},
          {"strides": [2, 2], "paddings": [0, 0], "dilations": [1, 1],
           "groups": 1}, ["Input"]),
    ]


def _configs_bwd(fwd_cfgs):
    def simple(op, ins, outs, attrs=None):
        def build(blk, scope):
            return op, ins(blk, scope), outs, (attrs or {})
        return build

    by_name = dict((n, b) for n, b, *_ in fwd_cfgs)
    cfgs = [(f"{name}_bwd", _bwd(by_name[name], *slots))
            for name, slots in _BWD_FAMILIES if name in by_name]
    cfgs += _conv_bwd_cfgs(simple)
    # fwd+bwd scans are ~3x the forward work: shorter scans keep the
    # table generation tractable without losing the marginal-slope
    # methodology (lo becomes 3)
    return [(n, b, {"steps": 12}) for n, b in cfgs]


def _rnn_cfg(op, gates, SB, ST, SD, outs, attrs):
    def build(blk, scope):
        xg = _f((SB, ST, gates * SD), "xg", blk)
        lv = blk.create_var(name="xg@@LOD")
        blk.append_op(type="randint", inputs={},
                      outputs={"Out": [lv.name]},
                      attrs={"shape": [SB], "low": 1, "high": ST + 1,
                             "dtype": "int32"})
        return op, {"Input": [xg],
                    "Weight": [_p((SD, gates * SD), "w", blk, scope)],
                    "Bias": [_p((1, gates * SD), "bias", blk, scope)]}, \
            outs, attrs
    return build


def _sig01(blk, shape, name):
    """uniform(0.05, 0.95) input (ops needing (0,1) or positive data)."""
    v = blk.create_var(name=name)
    blk.append_op(type="uniform_random", inputs={},
                  outputs={"Out": [v.name]},
                  attrs={"shape": list(shape), "min": 0.05, "max": 0.95,
                         "dtype": "float32"})
    return v.name


def _softmaxed(blk, shape, name):
    raw = _f(shape, name + "_raw", blk)
    v = blk.create_var(name=name)
    blk.append_op(type="softmax", inputs={"X": [raw]},
                  outputs={"Out": [v.name]}, attrs={"axis": -1})
    return v.name


def _configs_special():
    """Configs needing bespoke graph construction."""
    B, T, D = 32, 128, 768
    SB, ST, SD = 64, 50, 64

    def where_build(blk, scope):
        x = _f((B, T, D), "x", blk)
        y = _f((B, T, D), "y", blk)
        c = blk.create_var(name="cond")
        blk.append_op(type="greater_than",
                      inputs={"X": [x], "Y": [y]},
                      outputs={"Out": [c.name]}, attrs={})
        return "where", {"Condition": [c.name], "X": [x], "Y": [y]}, \
            {"Out": 1}, {}

    def seqpool_concat_build(blk, scope):
        ins = []
        for i in range(4):
            x = _f((SB, ST, SD), f"x{i}", blk)
            lv = blk.create_var(name=f"x{i}@@LOD")
            blk.append_op(type="randint", inputs={},
                          outputs={"Out": [lv.name]},
                          attrs={"shape": [SB], "low": 1, "high": ST + 1,
                                 "dtype": "int32"})
            ins.append(x)
        return "fusion_seqpool_concat", {"X": ins}, {"Out": 1}, \
            {"pooltype": "SUM", "axis": 1}

    def seq_expand_build(blk, scope):
        x = _f((SB, 1, SD), "x", blk)
        y = _f((SB, ST, SD), "y", blk)
        for n, hi in (("x", 2), ("y", ST + 1)):
            lv = blk.create_var(name=f"{n}@@LOD")
            blk.append_op(type="randint", inputs={},
                          outputs={"Out": [lv.name]},
                          attrs={"shape": [SB], "low": 1, "high": hi,
                                 "dtype": "int32"})
        return "sequence_expand", {"X": [x], "Y": [y]}, {"Out": 1}, \
            {"ref_level": 0}

    def seq_mask_build(blk, scope):
        ids = _i((SB,), "lens", blk, high=ST)
        return "sequence_mask", {"X": [ids]}, {"Y": 1}, \
            {"maxlen": ST, "out_dtype": "float32"}

    def yolo_build(blk, scope):
        x = _f((8, 255, 13, 13), "x", blk)
        sz = blk.create_var(name="imgsz")
        blk.append_op(type="randint", inputs={},
                      outputs={"Out": [sz.name]},
                      attrs={"shape": [8, 2], "low": 416, "high": 417,
                             "dtype": "int32"})
        return "yolo_box", {"X": [x], "ImgSize": [sz.name]}, \
            {"Boxes": 1, "Scores": 1}, \
            {"anchors": [10, 13, 16, 30, 33, 23], "class_num": 80,
             "conf_thresh": 0.01, "downsample_ratio": 32,
             "clip_bbox": True}

    def box_clip_build(blk, scope):
        boxes = _f((2048, 4), "bx", blk)
        info = blk.create_var(name="iminfo")
        blk.append_op(type="uniform_random", inputs={},
                      outputs={"Out": [info.name]},
                      attrs={"shape": [1, 3], "min": 224.0, "max": 225.0,
                             "dtype": "float32"})
        return "box_clip", {"Input": [boxes], "ImInfo": [info.name]}, \
            {"Output": 1}, {}

    def seq_enum_build(blk, scope):
        ids = _i((2048, 1), "ids", blk, high=30000)
        return "sequence_enumerate", {"X": [ids]}, {"Out": 1}, \
            {"win_size": 2, "pad_value": 0}

    return [
        ("where", where_build),
        ("fusion_seqpool_concat", seqpool_concat_build),
        ("sequence_expand", seq_expand_build),
        ("sequence_mask", seq_mask_build),
        ("yolo_box", yolo_build),
        ("box_clip", box_clip_build),
        ("sequence_enumerate", seq_enum_build),
    ]


def _configs_optimizer():
    """optimizer_step rows: whole `opt.step()` over a transformer-shaped
    bag of ~200 small tensors, fused vs per-param — the CI perf gate
    watches the dispatch overhead the fused path exists to remove. These
    are direct benches (no fluid program): the eager optimizer IS the
    unit under test."""

    def direct(rule, fused, n_layers=14, hidden=64, steps=20):
        def bench():
            import jax
            import jax.numpy as jnp

            import paddle_tpu as paddle
            from paddle_tpu.core.tensor import Tensor
            from paddle_tpu.nn.layer.layers import Parameter

            H = hidden
            shapes = []
            for _ in range(n_layers):
                shapes += [(H, H)] * 4 + [(H,)] * 4
                shapes += [(H, 4 * H), (4 * H,), (4 * H, H), (H,)]
                shapes += [(H,), (H,)]
            rs = np.random.RandomState(0)
            params = [Parameter((rs.randn(*s) * 0.02).astype("f4"),
                                name=f"p{i}")
                      for i, s in enumerate(shapes)]
            grads = [Tensor(jnp.asarray(rs.randn(*s).astype("f4")))
                     for s in shapes]
            make = {"adam": paddle.optimizer.Adam,
                    "sgd": paddle.optimizer.SGD}[rule]
            opt = make(1e-3, parameters=params)
            if not fused:
                opt._use_fused = False
            for p, g in zip(params, grads):
                p.grad = g

            def run_n(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    opt.step()
                jax.block_until_ready([p._data for p in params])
                return time.perf_counter() - t0

            t0 = time.perf_counter()
            run_n(1)                      # compile + slot init
            compile_s = time.perf_counter() - t0
            e2e_s = run_n(1)
            run_n(5)
            run_n(steps)                  # warm both loop lengths
            slopes = []
            for _ in range(5):            # median of adjacent pairs
                t_lo = run_n(5)
                t_hi = run_n(steps)
                if t_hi > t_lo:
                    slopes.append((t_hi - t_lo) / (steps - 5))
            slopes.sort()
            dt = slopes[len(slopes) // 2] if slopes else e2e_s
            return {"e2e_us": round(e2e_s * 1e6, 1),
                    "step_us": round(dt * 1e6, 2),
                    "compile_s": round(compile_s, 2)}

        bench._direct = True
        return bench

    return [
        ("optimizer_step_adam_fused", direct("adam", True)),
        ("optimizer_step_adam_per_param", direct("adam", False)),
        ("optimizer_step_sgd_fused", direct("sgd", True)),
        ("optimizer_step_sgd_per_param", direct("sgd", False)),
    ]


def _configs_flash_decode():
    """flash_decode rows: single-token decode attention against a
    static KV cache (ops/attention.decode_attention), several cache
    lengths / batch sizes, split-K on vs off. Direct benches through
    the DISPATCHER: on the committed-baseline CPU backend both split
    settings time the XLA reference (identical by construction — the
    rows exist so the TPU driver's refresh shows the split-K delta);
    on TPU the pallas kernel engages with the requested split."""

    def direct(batch, heads, L, d, split, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import decode_attention

            rs = np.random.RandomState(0)
            q = jnp.asarray(rs.randn(batch, heads, 1, d).astype("f4"))
            k = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            v = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            length = jnp.int32(L * 3 // 4)

            fn = jax.jit(functools.partial(decode_attention,
                                           split_k=split))
            t0 = time.perf_counter()
            jax.block_until_ready(fn(q, k, v, length))
            compile_s = time.perf_counter() - t0

            def run_n(n):
                t0 = time.perf_counter()
                for _ in range(n):
                    out = fn(q, k, v, length)
                jax.block_until_ready(out)
                return time.perf_counter() - t0

            e2e_s = run_n(1)
            run_n(5)
            run_n(steps)
            slopes = []
            for _ in range(5):
                t_lo = run_n(5)
                t_hi = run_n(steps)
                if t_hi > t_lo:
                    slopes.append((t_hi - t_lo) / (steps - 5))
            slopes.sort()
            dt = slopes[len(slopes) // 2] if slopes else e2e_s
            return {"e2e_us": round(e2e_s * 1e6, 1),
                    "step_us": round(dt * 1e6, 2),
                    "compile_s": round(compile_s, 2)}

        bench._direct = True
        return bench

    return [
        ("flash_decode_b1_L2048_split", direct(1, 8, 2048, 64, 4)),
        ("flash_decode_b1_L2048_nosplit", direct(1, 8, 2048, 64, 1)),
        ("flash_decode_b8_L2048_split", direct(8, 8, 2048, 64, 4)),
        ("flash_decode_b8_L2048_nosplit", direct(8, 8, 2048, 64, 1)),
        ("flash_decode_b8_L8192_split", direct(8, 8, 8192, 64, 8)),
        ("flash_decode_b8_L8192_nosplit", direct(8, 8, 8192, 64, 1)),
        ("flash_decode_b32_L512_split", direct(32, 8, 512, 64, 4)),
    ]


def _configs_serving():
    """Serving-runtime kernel rows: the decode-step-with-slot-join
    shapes the continuous-batching engine runs every iteration.
    `decode_rowlens` is single-token decode attention with PER-ROW
    written counts (each serving slot at its own cache offset) vs the
    lockstep variant; `slot_join` is the prefill splice — a bucketed
    [1, H, P, D] K/V block lands in the pooled [S, H, L, D] cache at a
    TRACED slot index; `step_join` is one full engine iteration at the
    kernel level: splice one joining slot, then decode every slot at
    its own offset. On the committed-baseline CPU backend the decode
    rows time the XLA reference (the rows exist so the TPU driver's
    refresh shows the pallas delta)."""

    def rowlens(batch, heads, L, d, per_row, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import decode_attention

            rs = np.random.RandomState(0)
            q = jnp.asarray(rs.randn(batch, heads, 1, d).astype("f4"))
            k = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            v = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            if per_row:
                length = jnp.asarray(
                    rs.randint(L // 4, L, (batch,)), jnp.int32)
            else:
                length = jnp.int32(L * 3 // 4)
            fn = jax.jit(decode_attention)
            return _time_direct(lambda: fn(q, k, v, length), steps)

        bench._direct = True
        return bench

    def slot_join(S, heads, L, d, P, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.nn.layer.transformer import \
                MultiHeadAttention as MHA

            rs = np.random.RandomState(0)
            pool = MHA.StaticKVCache(
                jnp.zeros((S, heads, L, d), jnp.float32),
                jnp.zeros((S, heads, L, d), jnp.float32),
                jnp.zeros((S,), jnp.int32))
            kb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            vb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            fn = jax.jit(lambda c, s: MHA.static_kv_splice(
                c, s, kb, vb, jnp.int32(P)))
            slot = jnp.int32(S // 2)
            return _time_direct(lambda: fn(pool, slot), steps)

        bench._direct = True
        return bench

    def step_join(S, heads, L, d, P, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.nn.layer.transformer import \
                MultiHeadAttention as MHA
            from paddle_tpu.ops.attention import decode_attention

            rs = np.random.RandomState(0)
            pool = MHA.StaticKVCache(
                jnp.asarray(rs.randn(S, heads, L, d).astype("f4")),
                jnp.asarray(rs.randn(S, heads, L, d).astype("f4")),
                jnp.asarray(rs.randint(P, L - 1, (S,)), jnp.int32))
            kb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            vb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            q = jnp.asarray(rs.randn(S, heads, 1, d).astype("f4"))

            def one_iter(c, slot):
                c = MHA.static_kv_splice(c, slot, kb, vb, jnp.int32(P))
                return decode_attention(q, c.k, c.v, c.index + 1)

            fn = jax.jit(one_iter)
            slot = jnp.int32(0)
            return _time_direct(lambda: fn(pool, slot), steps)

        bench._direct = True
        return bench

    return [
        ("serving_decode_rowlens_b8_L2048", rowlens(8, 8, 2048, 64,
                                                    True)),
        ("serving_decode_lockstep_b8_L2048", rowlens(8, 8, 2048, 64,
                                                     False)),
        ("serving_slot_join_s8_L2048_P128", slot_join(8, 8, 2048, 64,
                                                      128)),
        ("serving_slot_join_s8_L512_P64", slot_join(8, 8, 512, 64,
                                                    64)),
        ("serving_step_join_s8_L2048", step_join(8, 8, 2048, 64, 128)),
        ("serving_step_join_s32_L512", step_join(32, 8, 512, 64, 64)),
    ]


def _configs_spec_decode():
    """Speculative-decoding kernel rows: the k-token VERIFY attention
    (ops/attention.verify_attention — the pending token + k-1 drafts
    against the cache at per-row offsets, causal within the block) vs
    the PLAIN single-token decode step over the same cache, k in
    {2, 4, 8} at batch 1 and 8. The verify-to-plain step ratio is the
    cost of widening one decode dispatch to k tokens — speculative
    decoding wins when (accepted run length) / (that ratio) > 1. On
    the committed-baseline CPU backend both route to the XLA reference
    (the rows exist so the TPU driver's refresh shows the pallas
    split-K verify delta)."""

    def step(batch, heads, L, d, T, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import (decode_attention,
                                                  verify_attention)

            rs = np.random.RandomState(0)
            q = jnp.asarray(rs.randn(batch, heads, T, d).astype("f4"))
            k = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            v = jnp.asarray(rs.randn(batch, heads, L, d).astype("f4"))
            length = jnp.asarray(rs.randint(L // 4, L, (batch,)),
                                 jnp.int32)
            fn = jax.jit(decode_attention if T == 1
                         else verify_attention)
            return _time_direct(lambda: fn(q, k, v, length), steps)

        bench._direct = True
        return bench

    rows = [(f"spec_decode_plain_b{b}_L2048", step(b, 8, 2048, 64, 1))
            for b in (1, 8)]
    rows += [(f"spec_decode_verify_k{T}_b{b}_L2048",
              step(b, 8, 2048, 64, T))
             for b in (1, 8) for T in (2, 4, 8)]
    return rows


def _configs_sharded_decode():
    """Sharded decode-step rows: the pooled decode-attention of the
    serving engines with the slot axis laid out data-parallel over a
    dp mesh and the kernel spec-annotated via
    `ops.attention.decode_shardings` (the ShardedServingEngine path),
    against the same shapes on a 1-device mesh. On this CPU harness the
    numbers measure structure/overhead, not bandwidth; the TPU driver
    refreshes them on real chips. Rows skip (not fail) when the host
    lacks the virtual 8-device mesh."""
    def sharded_step(S, heads, L, d, dp, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            from paddle_tpu.ops.attention import (decode_attention,
                                                  decode_shardings)

            devs = [dev for dev in jax.devices()
                    if dev.platform == "cpu"] or jax.devices()
            if len(devs) < dp:
                return {"skipped": f"needs {dp} devices (run with "
                        f"XLA_FLAGS=--xla_force_host_platform_"
                        f"device_count=8)"}
            mesh = Mesh(np.array(devs[:dp]), ("dp",))
            ns = NamedSharding(mesh, P("dp"))
            rs = np.random.RandomState(0)
            q = jax.device_put(
                jnp.asarray(rs.randn(S, heads, 1, d).astype("f4")), ns)
            k = jax.device_put(
                jnp.asarray(rs.randn(S, heads, L, d).astype("f4")), ns)
            v = jax.device_put(
                jnp.asarray(rs.randn(S, heads, L, d).astype("f4")), ns)
            length = jax.device_put(
                jnp.asarray(rs.randint(L // 4, L, (S,)), jnp.int32),
                ns)
            specs = {"q": ns, "kv": ns, "out": ns}

            def step(q, k, v, length):
                with decode_shardings(specs):
                    return decode_attention(q, k, v, length)

            fn = jax.jit(step)
            return _time_direct(lambda: fn(q, k, v, length), steps)

        bench._direct = True
        return bench

    return [
        ("sharded_decode_s8_L2048_dp1", sharded_step(8, 8, 2048, 64,
                                                     1)),
        ("sharded_decode_s8_L2048_dp8", sharded_step(8, 8, 2048, 64,
                                                     8)),
        ("sharded_decode_s32_L512_dp1", sharded_step(32, 8, 512, 64,
                                                     1)),
        ("sharded_decode_s32_L512_dp8", sharded_step(32, 8, 512, 64,
                                                     8)),
    ]


def _configs_paged_decode():
    """Paged decode-attention rows: one query token per slot against
    K/V reached THROUGH a [S, max_pages] int32 page table (the paged
    serving pool's per-step kernel call), across page sizes, logical
    cache lengths, and fp32 vs int8 pages (per-page scales dequantized
    at read time). Times the dispatcher: on the committed-baseline CPU
    backend that is the gather + XLA reference (the rows exist so the
    TPU driver's refresh shows the scalar-prefetch kernel delta vs the
    dense flash_decode rows above)."""

    def direct(batch, heads, L, d, psz, kv_dtype, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import paged_decode_attention
            from paddle_tpu.serving.paging import quantize_chunks

            rs = np.random.RandomState(0)
            mp = L // psz
            n_pages = batch * mp
            raw = jnp.asarray(
                rs.randn(n_pages + 1, heads, psz, d).astype("f4"))
            if kv_dtype == "int8":
                pages, scales = quantize_chunks(raw, jnp.int8, True)
            else:
                pages, scales = raw, None
            table = jnp.asarray(
                rs.permutation(n_pages).astype("i4").reshape(batch, mp))
            q = jnp.asarray(rs.randn(batch, heads, 1, d).astype("f4"))
            length = jnp.asarray(
                rs.randint(L // 4, L, (batch,)), jnp.int32)

            fn = jax.jit(lambda q, kp, vp, t, n: paged_decode_attention(
                q, kp, vp, scales, scales, t, n))
            return _time_direct(
                lambda: fn(q, pages, pages, table, length), steps)

        bench._direct = True
        return bench

    return [
        ("paged_decode_b8_L512_p16_f32", direct(8, 8, 512, 64, 16,
                                                "f32")),
        ("paged_decode_b8_L512_p16_int8", direct(8, 8, 512, 64, 16,
                                                 "int8")),
        ("paged_decode_b8_L2048_p16_f32", direct(8, 8, 2048, 64, 16,
                                                 "f32")),
        ("paged_decode_b8_L2048_p64_f32", direct(8, 8, 2048, 64, 64,
                                                 "f32")),
        ("paged_decode_b8_L2048_p64_int8", direct(8, 8, 2048, 64, 64,
                                                  "int8")),
        ("paged_decode_b8_L8192_p64_f32", direct(8, 8, 8192, 64, 64,
                                                 "f32")),
        ("paged_decode_b8_L8192_p64_int8", direct(8, 8, 8192, 64, 64,
                                                  "int8")),
    ]


def _configs_paged_verify():
    """Paged speculative-verify rows: the k-token verify block against
    K/V reached through the page table (the paged spec pool's per-step
    kernel call — `ops.attention.paged_verify_attention`), k in
    {2, 4}, fp32 vs int8 pages. The verify-to-paged-decode step ratio
    is the paged analogue of the spec_decode_verify rows: speculative
    decoding on the paged pool wins when accepted run length beats it.
    On the committed-baseline CPU backend the dispatcher routes to
    gather + the dense verify reference (the rows exist so the TPU
    driver's refresh shows the block-table pallas verify delta)."""

    def direct(batch, heads, L, d, psz, T, kv_dtype, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import paged_verify_attention
            from paddle_tpu.serving.paging import quantize_chunks

            rs = np.random.RandomState(0)
            mp = L // psz
            n_pages = batch * mp
            raw = jnp.asarray(
                rs.randn(n_pages + 1, heads, psz, d).astype("f4"))
            if kv_dtype == "int8":
                pages, scales = quantize_chunks(raw, jnp.int8, True)
            else:
                pages, scales = raw, None
            table = jnp.asarray(
                rs.permutation(n_pages).astype("i4").reshape(batch, mp))
            q = jnp.asarray(rs.randn(batch, heads, T, d).astype("f4"))
            length = jnp.asarray(
                rs.randint(L // 4, L, (batch,)), jnp.int32)

            fn = jax.jit(
                lambda q, kp, vp, t, n: paged_verify_attention(
                    q, kp, vp, scales, scales, t, n))
            return _time_direct(
                lambda: fn(q, pages, pages, table, length), steps)

        bench._direct = True
        return bench

    return [
        (f"paged_verify_k{T}_{dt}",
         direct(8, 8, 2048, 64, 16, T, dt))
        for T in (2, 4) for dt in ("f32", "int8")
    ]


def _configs_lora_int8():
    """Multi-tenant serving kernel rows (PR 15). `lora_decode_*`: the
    base decode-shaped linear PLUS the gathered per-row LoRA delta
    (`ops.quant.lora_delta` — adapter ids gathered from stacked
    [n_adapters, d, r] banks) vs the base linear alone
    (`lora_base_b{b}`): the step_us gap is the cost of carrying
    adapters in every decode dispatch, r in {8, 32} at batch 1 and 8.
    `int8_matmul_vs_f32`: the scaled-int8 weight matmul
    (`ops.quant.int8_matmul` — int8 storage, fp32 accumulate) against
    the same-shape fp32 matmul, measured PAIRED (measure_pair) so the
    sub-2x delta is stable on this 1-core box; step_us is the int8
    side, f32_step_us/int8_speedup ride along. On the
    committed-baseline CPU backend both route through XLA (the rows
    exist so the TPU driver's refresh shows the pallas tile + weight-
    traffic delta)."""

    def lora(batch, d, r, with_delta, n_adapters=8, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops import quant as Q

            rs = np.random.RandomState(0)
            x = jnp.asarray(rs.randn(batch, 1, d).astype("f4"))
            w = jnp.asarray((rs.randn(d, d) * 0.05).astype("f4"))
            b = jnp.asarray(rs.randn(d).astype("f4"))
            Ab = jnp.asarray(
                (rs.randn(n_adapters, d, r) * 0.05).astype("f4"))
            Bb = jnp.asarray(
                (rs.randn(n_adapters, r, d) * 0.05).astype("f4"))
            ids = jnp.asarray(rs.randint(0, n_adapters, (batch,)),
                              jnp.int32)

            if with_delta:
                fn = jax.jit(lambda a, wa, wb, i: (
                    a @ w + b + Q.lora_delta(a, wa, wb, i)))
                return _time_direct(lambda: fn(x, Ab, Bb, ids), steps)
            fn = jax.jit(lambda a: a @ w + b)
            return _time_direct(lambda: fn(x), steps)

        bench._direct = True
        return bench

    def int8_vs_f32(m, d, n, steps=30):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops import quant as Q

            rs = np.random.RandomState(0)
            x = jnp.asarray(rs.randn(m, d).astype("f4"))
            w = jnp.asarray((rs.randn(d, n) * 0.05).astype("f4"))
            wq, ws = Q.quantize_int8_weight(w)
            f_int8 = jax.jit(lambda a: Q.int8_matmul(a, wq, ws))
            f_f32 = jax.jit(lambda a: a @ w)
            dt8, dt32 = measure_pair(lambda: f_int8(x),
                                     lambda: f_f32(x))
            return {"step_us": round(dt8 * 1e6, 2),
                    "f32_step_us": round(dt32 * 1e6, 2),
                    "int8_speedup": round(dt32 / max(dt8, 1e-12), 3)}

        bench._direct = True
        return bench

    rows = [(f"lora_base_b{b}", lora(b, 768, 8, False))
            for b in (1, 8)]
    rows += [(f"lora_decode_r{r}_b{b}", lora(b, 768, r, True))
             for r in (8, 32) for b in (1, 8)]
    rows.append(("int8_matmul_vs_f32", int8_vs_f32(8, 768, 3072)))
    return rows


def _configs_prefix_attach():
    """Radix prefix-attach rows (PR 16): the pattach program's kernel
    asymmetry, measured PAIRED. Tail side = verify-mode attention of
    only the DIVERGENT TAIL (t pages of queries) reading the m trie-
    matched pages plus itself back through the page table — the attach
    program's attention call, whose cost scales with the tail. Full
    side = the same `paged_verify_attention` with queries for the
    WHOLE prompt at identical total depth — what a whole-prompt
    prefill pays when the radix cache misses. Both sides write K/V
    page-granularly in the engine, so the attention pair isolates the
    reuse win; step_us is the tail side, full_step_us/attach_speedup
    ride along. m in {4, 16} matched pages, t in {1, 4} tail pages at
    page_size 16 — the speedup should grow with m/t, and the perf
    gate's attach pair pins the m16_t1 ratio."""

    def direct(m, t, heads=8, d=64, psz=16):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.ops.attention import paged_verify_attention

            rs = np.random.RandomState(0)
            W = m + t                       # clipped table width
            N, T = W * psz, t * psz         # full vs tail tokens
            pages = jnp.asarray(
                rs.randn(W + 1, heads, psz, d).astype("f4"))
            table = jnp.asarray(
                rs.permutation(W).astype("i4").reshape(1, W))
            q_tail = jnp.asarray(rs.randn(1, heads, T, d).astype("f4"))
            q_full = jnp.asarray(rs.randn(1, heads, N, d).astype("f4"))
            length = jnp.asarray([N], jnp.int32)

            fn = jax.jit(lambda q: paged_verify_attention(
                q, pages, pages, None, None, table, length))
            dt_t, dt_f = measure_pair(lambda: fn(q_tail),
                                      lambda: fn(q_full))
            return {"step_us": round(dt_t * 1e6, 2),
                    "full_step_us": round(dt_f * 1e6, 2),
                    "attach_speedup": round(dt_f / max(dt_t, 1e-12), 3)}

        bench._direct = True
        return bench

    return [(f"prefix_attach_m{m}_t{t}", direct(m, t))
            for m in (4, 16) for t in (1, 4)]


def _configs_join_donation():
    """Zero-copy join rows (PR 17): the join family's splice write,
    DONATED vs undonated, measured PAIRED. Every join program now
    takes the pool carry with donate_argnums, so the prompt splice is
    an in-place scatter instead of a whole-pool copy + scatter —
    step_us is the donated side (what the engine actually dispatches),
    copy_step_us the undonated twin (the same program without the
    alias, i.e. what every join paid before this PR), and
    inplace_speedup their ratio. Dense = the bucketed [1, H, P, D]
    K/V block landing in the pooled [S, H, L, D] cache at a traced
    slot (static_kv_splice, the dense join's hot write); paged = the
    page-granular scatter of the same block into the global page pool
    (write_prompt_pages, the pjoin/prefill hot write). The donated
    side ping-pongs the carry through a holder — each call consumes
    the previous call's output, exactly like the engine's
    self._state reassignment."""

    def dense(S, heads, L, d, P, steps=20):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.nn.layer.transformer import \
                MultiHeadAttention as MHA

            rs = np.random.RandomState(0)
            kb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            vb = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))

            def splice(c, s):
                return MHA.static_kv_splice(c, s, kb, vb,
                                            jnp.int32(P))

            def mk_pool():
                return MHA.StaticKVCache(
                    jnp.zeros((S, heads, L, d), jnp.float32),
                    jnp.zeros((S, heads, L, d), jnp.float32),
                    jnp.zeros((S,), jnp.int32))

            fn_copy = jax.jit(splice)
            fn_don = jax.jit(splice, donate_argnums=0)
            pool = mk_pool()
            holder = [mk_pool()]
            slot = jnp.int32(S // 2)

            def run_donated():
                holder[0] = fn_don(holder[0], slot)
                return holder[0]

            dt_d, dt_c = measure_pair(run_donated,
                                      lambda: fn_copy(pool, slot),
                                      steps=steps)
            return {"step_us": round(dt_d * 1e6, 2),
                    "copy_step_us": round(dt_c * 1e6, 2),
                    "inplace_speedup": round(
                        dt_c / max(dt_d, 1e-12), 3)}

        bench._direct = True
        return bench

    def paged(n_pages, heads, psz, d, P, steps=20):
        def bench():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.serving.paging import (pages_for,
                                                   write_prompt_pages)

            rs = np.random.RandomState(0)
            kv = jnp.asarray(rs.randn(1, heads, P, d).astype("f4"))
            ids = jnp.asarray(
                rs.permutation(n_pages)[:pages_for(P, psz)]
                .astype("i4"))

            def splice(pages):
                return write_prompt_pages(pages, None, ids, kv,
                                          False)[0]

            def mk_pages():
                return jnp.zeros((n_pages + 1, heads, psz, d),
                                 jnp.float32)

            fn_copy = jax.jit(splice)
            fn_don = jax.jit(splice, donate_argnums=0)
            pages = mk_pages()
            holder = [mk_pages()]

            def run_donated():
                holder[0] = fn_don(holder[0])
                return holder[0]

            dt_d, dt_c = measure_pair(run_donated,
                                      lambda: fn_copy(pages),
                                      steps=steps)
            return {"step_us": round(dt_d * 1e6, 2),
                    "copy_step_us": round(dt_c * 1e6, 2),
                    "inplace_speedup": round(
                        dt_c / max(dt_d, 1e-12), 3)}

        bench._direct = True
        return bench

    return [
        ("join_inplace_vs_copy_dense", dense(8, 8, 2048, 64, 128)),
        ("join_inplace_vs_copy_paged", paged(256, 8, 16, 64, 128)),
    ]


def measure(run, args=(), *, steps=30, lo=5, k=5, detail=False):
    """THE timing methodology, reusable: median-of-k marginal per-call
    seconds of `run(*args)` via two-point pair slopes — run `lo` calls
    and `steps` calls back to back, the slope (t_hi - t_lo)/(steps -
    lo) cancels the per-batch dispatch constant, and the median over k
    pairs rides out this box's 1-core scheduling noise. The kernel
    autotuner (paddle_tpu.tuning.autotune) and every direct op-bench
    config share this one function, so tuned-vs-fallback comparisons
    are measured exactly like the committed baselines. First call
    compiles (jit warmup) and is excluded. Returns seconds, or the
    {step_s, e2e_s, compile_s} dict with detail=True."""
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(run(*args))
    compile_s = time.perf_counter() - t0

    def run_n(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = run(*args)
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    e2e_s = run_n(1)
    run_n(lo)
    run_n(steps)
    slopes = []
    for _ in range(k):
        t_lo = run_n(lo)
        t_hi = run_n(steps)
        if t_hi > t_lo:
            slopes.append((t_hi - t_lo) / (steps - lo))
    slopes.sort()
    dt = slopes[len(slopes) // 2] if slopes else e2e_s
    if detail:
        return {"step_s": dt, "e2e_s": e2e_s, "compile_s": compile_s}
    return dt


def measure_pair(run_a, run_b, *, steps=20, lo=5, k=6):
    """PAIRED A/B measurement: each repeat times (a, b) back to back
    with the order alternating between repeats, and the medians of the
    per-repeat slopes are returned as (dt_a, dt_b) seconds. Sub-2x
    comparisons on this 1-core box are only stable paired — unpaired
    group medians drift 2%+ (the PR 8 tracing-overhead lesson); the
    perf gate's tuned-vs-fallback rows ride this."""
    import jax

    def run_n(run, n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = run()
        jax.block_until_ready(out)
        return time.perf_counter() - t0

    for r in (run_a, run_b):          # compile + cache warm, both
        jax.block_until_ready(r())
        run_n(r, lo)
        run_n(r, steps)
    d_a, d_b = [], []
    for i in range(k):
        order = (run_a, run_b) if i % 2 == 0 else (run_b, run_a)
        got = {}
        for r in order:
            t_lo = run_n(r, lo)
            t_hi = run_n(r, steps)
            got[id(r)] = max(0.0, (t_hi - t_lo) / (steps - lo))
        d_a.append(got[id(run_a)])
        d_b.append(got[id(run_b)])
    d_a.sort()
    d_b.sort()
    return d_a[len(d_a) // 2], d_b[len(d_b) // 2]


def _time_direct(run, steps):
    """Shared timing scaffold for direct (non-Program) benches — the
    `measure()` methodology formatted as an OP_BENCH row."""
    r = measure(run, steps=steps, detail=True)
    return {"e2e_us": round(r["e2e_s"] * 1e6, 1),
            "step_us": round(r["step_s"] * 1e6, 2),
            "compile_s": round(r["compile_s"], 2)}


def bench_one(name, builder, steps=30):
    import paddle_tpu.fluid as fluid

    main, startup = fluid.Program(), fluid.Program()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.unique_name.guard(), fluid.program_guard(main,
                                                            startup):
            blk = main.global_block()
            built = builder(blk, scope)
            op, ins, outs, attrs = built[:4]
            wrt_slots = built[4] if len(built) > 4 else None
            out_map = {}
            for slot, n_out in outs.items():
                out_map[slot] = [
                    blk.create_var(name=f"ob_{slot}_{i}").name
                    for i in range(n_out)]
            blk.append_op(type=op, inputs=ins, outputs=out_map,
                          attrs=attrs)
            # persistable accumulator consuming the op output: without
            # it the scan carry ignores the op and XLA dead-code
            # eliminates every step but the unrolled last one
            first_out = out_map[next(iter(out_map))][0]
            red = blk.create_var(name="ob_red")
            blk.append_op(type="reduce_sum",
                          inputs={"X": [first_out]},
                          outputs={"Out": [red.name]},
                          attrs={"dim": [], "reduce_all": True,
                                 "keep_dim": False})
            cst = blk.create_var(name="ob_cst")
            blk.append_op(type="cast", inputs={"X": [red]},
                          outputs={"Out": [cst.name]},
                          attrs={"in_dtype": "float32",
                                 "out_dtype": "float32"})
            acc = blk.create_var(name="ob_acc", shape=[1],
                                 dtype="float32")
            acc.persistable = True
            blk.append_op(type="elementwise_add",
                          inputs={"X": ["ob_acc"], "Y": [cst]},
                          outputs={"Out": ["ob_acc"]}, attrs={})
            if wrt_slots:
                # backward config: differentiate the scalar reduction
                # w.r.t. the named input slots (jax_autodiff op) and
                # fold every grad into the accumulator so neither pass
                # can be dead-code eliminated out of the scan
                wrt_vars = [blk.var(n) for slot in wrt_slots
                            for n in ins[slot]]
                grads = fluid.gradients([red], wrt_vars)
                for i, g in enumerate(grads):
                    rg = blk.create_var(name=f"ob_gred_{i}")
                    blk.append_op(type="reduce_sum",
                                  inputs={"X": [g.name]},
                                  outputs={"Out": [rg.name]},
                                  attrs={"dim": [], "reduce_all": True,
                                         "keep_dim": False})
                    blk.append_op(type="elementwise_add",
                                  inputs={"X": ["ob_acc"],
                                          "Y": [rg.name]},
                                  outputs={"Out": ["ob_acc"]}, attrs={})
        scope.set_value("ob_acc", np.zeros(1, np.float32))
        exe = fluid.Executor()
        exe.run(startup)
        fetch = ["ob_acc"]

        t0 = time.perf_counter()
        exe.run(main, {}, fetch)          # compile
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        exe.run(main, {}, fetch)
        e2e_s = time.perf_counter() - t0

        for n in (steps, 5):                  # compile both scan lengths
            exe.run_n(main, {}, fetch, n=n)
        slopes = []
        for _ in range(5):                    # median of adjacent pairs
            t0 = time.perf_counter()
            exe.run_n(main, {}, fetch, n=5)
            t_lo = time.perf_counter() - t0
            t0 = time.perf_counter()
            exe.run_n(main, {}, fetch, n=steps)
            t_hi = time.perf_counter() - t0
            if t_hi > t_lo:
                slopes.append((t_hi - t_lo) / (steps - 5))
        slopes.sort()
        dt = slopes[len(slopes) // 2] if slopes else 0.0
    return {"e2e_us": round(e2e_s * 1e6, 1),
            "step_us": round(dt * 1e6, 2),
            "compile_s": round(compile_s, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="pin to the virtual-CPU jax backend (the axon "
                         "site hook otherwise grabs the tunnel chip)")
    ap.add_argument("--quick", action="store_true",
                    help="first 8 configs only")
    ap.add_argument("--ops", default="", help="comma-separated subset")
    ap.add_argument("--out", default=BASELINE)
    ap.add_argument("--compare", action="store_true",
                    help="compare against the committed baseline; exit 1 "
                         "when any op's step_us regressed >2x")
    ap.add_argument("--merge", action="store_true",
                    help="merge benched ops into the existing table "
                         "instead of clobbering it (e.g. generate only "
                         "the new _bwd rows: --ops ... --merge)")
    args = ap.parse_args()
    if args.cpu:
        sys.path.insert(0, REPO)
        import _cpu_debug  # noqa: F401  (forces the cpu backend)

    cfgs = _configs()
    if args.ops:
        want = set(args.ops.split(","))
        cfgs = [c for c in cfgs if c[0] in want]
    elif args.quick:
        cfgs = cfgs[:8]

    results = {}
    for name, builder, *rest in cfgs:
        opts = rest[0] if rest else {}
        try:
            if getattr(builder, "_direct", False):
                results[name] = builder()
            else:
                results[name] = bench_one(name, builder, **opts)
        except Exception as e:  # record, keep the table alive
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        r = results[name]
        print(f"{name:28s} {json.dumps(r)}", file=sys.stderr)

    import jax

    record = {"backend": jax.default_backend(),
              "ops": results}
    if args.merge and not args.compare:
        try:
            with open(args.out) as f:
                base = json.load(f)
        except Exception:
            base = {"backend": record["backend"], "ops": {}}
        if base.get("backend") != record["backend"]:
            print(f"refusing to merge across backends "
                  f"({base.get('backend')} vs {record['backend']})",
                  file=sys.stderr)
            sys.exit(1)
        base["ops"].update(results)
        record = base
    if args.compare:
        try:
            with open(BASELINE) as f:
                base = json.load(f)
        except Exception:
            print("no baseline to compare against", file=sys.stderr)
            base = None
        bad = []
        if base and base.get("backend") == record["backend"]:
            for op, r in results.items():
                b = base["ops"].get(op, {})
                if "step_us" in r and "step_us" in b and \
                        b["step_us"] > 0 and \
                        r["step_us"] > 2.0 * b["step_us"]:
                    bad.append((op, b["step_us"], r["step_us"]))
        for op, old, new in bad:
            print(f"REGRESSION {op}: {old}us -> {new}us", file=sys.stderr)
        print(json.dumps({"regressions": len(bad)}))
        sys.exit(1 if bad else 0)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
    print(json.dumps({"ops_benchmarked": len(results),
                      "out": args.out}))


if __name__ == "__main__":
    main()
