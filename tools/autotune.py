#!/usr/bin/env python
"""Kernel autotune CLI: sweep pallas block configs, persist winners.

Front end over `paddle_tpu.tuning.autotune`: enumerate candidate
configs per (kernel, head_dim, seq bucket, dtype) key, time each with
the shared `tools/op_bench.measure` harness, prune candidates whose
analytic roofline floor (profiler.costs.DeviceSpec) already exceeds
the incumbent, and record winners keyed by this host's device_kind.

Usage:
  python tools/autotune.py --sweep flash_decode          # one kernel
  python tools/autotune.py --sweep all --out /tmp/t.json # everything
  python tools/autotune.py --smoke --dry-run             # CI smoke:
                                   # tiny key set, winners printed,
                                   # nothing written (scripts/ci.sh)
  python tools/autotune.py --sweep flash_decode --merge  # fold the
                                   # winners into the COMMITTED table
                                   # (paddle_tpu/tuning/tables/
                                   # default.json) under this device
  python tools/autotune.py --init  # regenerate the committed
                                   # fallback tier from the hand-
                                   # picked heuristics ('any' entries)
  python tools/autotune.py --show  # render the active table

Run sweeps STRICTLY alone on the chip (two jax processes contend on
the tunnel). On CPU the decode/verify dispatchers run their reference
composition (config-invariant), so a CPU sweep only proves mechanics —
real block wins need the device; the committed 'any' tier keeps
untuned devices bit-identical to the hand-picked constants either way.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr)


#: --smoke: the CI key set — one cheap key per sweep-worthy kernel
#: family, small enough for seconds on the CPU pin
SMOKE_KEYS = {
    "flash_decode": [(64, 512, "float32")],
    "int8_matmul": [(256, 256, "float32")],
    "lora_matmul": [(256, 8, "float32")],
}


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", default=None,
                    help="comma-separated kernels (or 'all'): "
                         "flash_fwd,flash_bwd,flash_decode,"
                         "flash_verify,paged_flash_decode")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI key set (flash_decode d64/L512) "
                         "with a short measurement budget")
    ap.add_argument("--dry-run", action="store_true",
                    help="print winners, write nothing")
    ap.add_argument("--merge", action="store_true",
                    help="fold winners into the committed default "
                         "table (device-keyed) instead of --out")
    ap.add_argument("--out", default=None,
                    help="write the swept table here (default: print)")
    ap.add_argument("--init", action="store_true",
                    help="regenerate the committed fallback tier from "
                         "the hand-picked heuristics")
    ap.add_argument("--show", action="store_true",
                    help="render the active table and exit")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20,
                    help="measurement scan length per candidate")
    ap.add_argument("--k", type=int, default=5,
                    help="median-of-k pair slopes per candidate")
    ap.add_argument("--cpu", action="store_true",
                    help="pin to the virtual-CPU jax backend")
    args = ap.parse_args(argv)
    if args.cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import _cpu_debug  # noqa: F401

    from paddle_tpu.tuning import autotune as AT
    from paddle_tpu.tuning import table as TBL

    if args.show:
        t = TBL.get_table()
        rows = t.entries() if t is not None else []
        for dev, kern, key, cfg in rows:
            print(f"{dev:12s} {kern:20s} {key:32s} {json.dumps(cfg)}")
        print(f"# {len(rows)} entries "
              f"(device tier: {TBL.current_device_kind()!r})")
        return 0

    if args.init:
        tbl = TBL.TuningTable()
        try:
            tbl.merge(TBL.TuningTable.load(TBL.committed_table_path()))
        except TBL.TableError:
            pass
        for kernel, key, cfg in AT.fallback_entries():
            tbl.put(kernel, key, cfg, device_kind="any")
        tbl.save(TBL.committed_table_path())
        _log(f"wrote {len(tbl)} entries -> "
             f"{TBL.committed_table_path()}")
        return 0

    if not args.sweep and not args.smoke:
        ap.error("one of --sweep/--smoke/--init/--show is required")

    if args.smoke:
        keysets = dict(SMOKE_KEYS)
        args.steps = min(args.steps, 10)
        args.k = min(args.k, 3)
    else:
        kernels = (list(AT.DEFAULT_KEYS) if args.sweep == "all"
                   else args.sweep.split(","))
        keysets = {}
        for kern in kernels:
            if kern not in AT.DEFAULT_KEYS:
                ap.error(f"unknown kernel {kern!r}")
            keysets[kern] = AT.DEFAULT_KEYS[kern]

    measurer = AT.default_measurer(batch=args.batch, heads=args.heads,
                                   steps=args.steps, k=args.k)
    device = TBL.current_device_kind()
    swept = TBL.TuningTable()
    reports = []
    for kernel, keys in keysets.items():
        for key in keys:
            _log(f"sweep {kernel} {TBL.key_str(key)} "
                 f"({len(AT.candidates(kernel, key))} candidates)")
            rep = AT.sweep_key(kernel, key, measurer=measurer,
                               batch=args.batch, heads=args.heads,
                               log=_log)
            reports.append(rep)
            AT.apply_report(swept, rep, device_kind=device)
            _log(f"  winner {rep['winner']} {rep['step_us']}us "
                 f"(fallback {rep['fallback']} {rep['fallback_us']}us,"
                 f" timed {rep['timed']}, pruned {rep['pruned']})")

    print(json.dumps({"device_kind": device, "swept": len(reports),
                      "winners": reports}, indent=1))
    if args.dry_run:
        _log("dry run: nothing written")
        return 0
    if args.merge:
        target = TBL.committed_table_path()
        tbl = TBL.TuningTable()
        try:
            tbl.merge(TBL.TuningTable.load(target))
        except TBL.TableError:
            pass
        tbl.merge(swept)
        tbl.save(target)
        _log(f"merged {len(reports)} winners into {target}")
    elif args.out:
        swept.save(args.out)
        _log(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
