#!/usr/bin/env python
"""Claim-hygiene gate: README bench headlines must match BENCH_DETAILS.json.

Round-3 and round-4 reviews both caught README/commit headlines quoting
numbers above the committed artifact of record (MNIST in r03, CTR in r04).
This check makes that impossible to repeat silently: every throughput row
in README's bench table is parsed and compared against the corresponding
BENCH_DETAILS.json median; any README claim more than TOLERANCE above the
artifact fails CI.

Claims may be *below* the artifact by any amount (sandbagging is honest),
and may exceed it by at most TOLERANCE (rounding, e.g. "~2700" for 2708).
"""

import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOLERANCE = 0.02  # README may exceed the artifact by at most 2% (rounding)

# (row-identifying regex, claim-extracting regex on the throughput cell,
#  BENCH_DETAILS path, human name). The claim regex must yield a float in
#  the artifact's units after the named multiplier is applied.
CHECKS = [
    (r"ERNIE-base fine-tune", r"~?([\d.]+)(k?)\s*seq/s", ("ernie", "value"), "ernie seq/s"),
    (r"ResNet-50 train", r"~?([\d.]+)(k?)\s*imgs/s", ("resnet50", "value"), "resnet50 imgs/s"),
    (r"fluid static MNIST", r"~?([\d.]+)(M?)\s*imgs/s", ("mnist", "value"), "mnist imgs/s"),
    (r"CTR-DNN", r"~?([\d.]+)(k?)\s*ex/s", ("ctr_ps", "value"), "ctr ex/s"),
    (r"ERNIE long-context", r"~?([\d.]+)()\s*seq/s", ("ernie_long", "value"), "ernie_long seq/s"),
    (r"Long-context flash attention", r"~?([\d.]+)()x XLA", ("long_context", "value"), "flash x-vs-XLA"),
    (r"Paged KV pool", r"~?([\d.]+)()x peak concurrent", ("serving_paged", "value"), "serving_paged x-concurrency"),
    (r"Speculative decoding \(self-draft n-gram, k=8, serving pool", r"~?([\d.]+)()x tokens/s", ("decode_throughput", "speculative", "b1", "speedup"), "speculative x-tokens/s"),
    (r"Paged speculative decoding", r"~?([\d.]+)()x tokens/s", ("serving_paged_spec", "value"), "paged-spec x-tokens/s"),
    (r"Multi-tenant serving", r"~?([\d.]+)()x aggregate tokens/s", ("serving_multitenant", "value"), "multitenant x-tokens/s"),
    (r"Radix prefix cache", r"~?([\d.]+)()x lower TTFT", ("serving_radix", "value"), "serving_radix x-ttft-at-depth"),
    (r"Traffic shaping", r"~?([\d.]+)()x lower interactive p99 TTFT", ("serving_slo", "value"), "serving_slo x-interactive-ttft"),
    (r"Sharded serving", r"~?([\d.]+)()x lower decode-step p50", ("serving_sharded", "value"), "serving_sharded x-step-p50"),
    (r"Zero-warmup restart", r"~?([\d.]+)()x faster time-to-ready", ("cold_start", "value"), "cold_start x-ready"),
]

MULT = {"": 1.0, "k": 1e3, "M": 1e6}


def main():
    readme = open(os.path.join(ROOT, "README.md")).read()
    details = json.load(open(os.path.join(ROOT, "BENCH_DETAILS.json")))

    failures = []
    checked = 0
    for row_re, claim_re, path, name in CHECKS:
        rows = [ln for ln in readme.splitlines() if ln.startswith("|") and re.search(row_re, ln)]
        if not rows:
            failures.append(f"{name}: README row matching /{row_re}/ not found")
            continue
        cells = [c.strip() for c in rows[0].strip().strip("|").split("|")]
        if len(cells) < 2:
            failures.append(f"{name}: bench row has no throughput column: {rows[0][:90]}")
            continue
        m = re.search(claim_re, cells[1])  # column 2 = Throughput
        if not m:
            failures.append(f"{name}: no claim matching /{claim_re}/ in throughput cell: {cells[1][:90]}")
            continue
        claimed = float(m.group(1)) * MULT[m.group(2)]
        try:
            node = details
            for k in path:
                node = node[k]
            artifact = float(node)
        except (KeyError, TypeError, ValueError) as e:
            failures.append(f"{name}: BENCH_DETAILS path {path} unreadable: {e!r}")
            continue
        checked += 1
        if claimed > artifact * (1.0 + TOLERANCE):
            failures.append(
                f"{name}: README claims {claimed:g} but BENCH_DETAILS says {artifact:g} "
                f"(over by {claimed / artifact - 1:.1%}, tolerance {TOLERANCE:.0%})"
            )
        else:
            print(f"ok: {name}: README {claimed:g} <= artifact {artifact:g} (+{TOLERANCE:.0%})")

    # traced-overhead hygiene (PR 8): the serving bench row must carry
    # the tracing-ON-vs-OFF claim, and the artifact must back it — the
    # bench ASSERTS <2% in-run, so a missing/over-budget record means
    # the observability layer regressed or the row went stale.
    serving_rows = [ln for ln in readme.splitlines()
                    if ln.startswith("|") and re.search(
                        r"Continuous-batching serving", ln)]
    if not serving_rows:
        failures.append("traced overhead: README 'Continuous-batching "
                        "serving' bench row not found")
    elif not re.search(r"[Tt]raced overhead.*<\s*2\s*%",
                       serving_rows[0]):
        failures.append("traced overhead: serving bench row does not "
                        "mention the asserted '<2%' traced overhead")
    else:
        try:
            ov = details["serving_throughput"]["trace_overhead"]
            pct = float(ov["overhead_pct"])
        except (KeyError, TypeError, ValueError) as e:
            failures.append(f"traced overhead: BENCH_DETAILS "
                            f"serving_throughput.trace_overhead "
                            f"unreadable: {e!r}")
        else:
            checked += 1
            if pct >= 2.0:
                failures.append(
                    f"traced overhead: artifact records {pct}% >= the "
                    f"2% budget the bench asserts")
            else:
                print(f"ok: traced overhead: README '<2%' backed by "
                      f"artifact {pct}%")

    # HBM-ledger hygiene (PR 9): the serving benches assert the
    # snapshot memory section equals the analytic pool+weight footprint
    # exactly — the committed artifact must carry that record, true.
    for metric in ("serving_throughput", "serving_paged"):
        try:
            ml = details[metric]["memory_ledger"]
            ok = bool(ml["exact_match"]) and \
                int(ml["total_bytes"]) == int(ml["analytic_bytes"])
        except (KeyError, TypeError, ValueError) as e:
            failures.append(f"memory ledger: BENCH_DETAILS "
                            f"{metric}.memory_ledger unreadable: {e!r}")
        else:
            checked += 1
            if not ok:
                failures.append(
                    f"memory ledger: {metric} records "
                    f"total {ml.get('total_bytes')} != analytic "
                    f"{ml.get('analytic_bytes')}")
            else:
                print(f"ok: memory ledger: {metric} exact "
                      f"({ml['total_bytes']} bytes)")

    if failures:
        print("README bench-claim check FAILED:", file=sys.stderr)
        for f in failures:
            print("  - " + f, file=sys.stderr)
        return 1
    print(f"README bench claims consistent with BENCH_DETAILS.json ({checked} rows checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
