#!/usr/bin/env python
"""Prometheus text-format dump of ServingMetrics (+ tracer counters).

The serving runtime's `ServingMetrics.snapshot()` is a nested dict;
operators scrape flat Prometheus metrics. This CLI renders the one into
the other via `paddle_tpu.serving.metrics.to_prometheus` (the schema of
record is `SNAPSHOT_DOCS` — every snapshot key is documented there and
the doc test pins the two in sync). Usage:

    # render a saved snapshot (json.dump(engine.metrics.snapshot()))
    python tools/metrics_dump.py --snapshot snap.json [-o out.prom]

    # drive a tiny in-process pool and dump ITS metrics (self-test /
    # schema preview; runs on the CPU pin, no hardware needed)
    JAX_PLATFORMS=cpu python tools/metrics_dump.py --demo

In-process, prefer the library route:

    from paddle_tpu.serving import to_prometheus
    text = to_prometheus(engine.metrics.snapshot(), tracer=tracer)
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _demo_snapshot():
    """Serve a few requests through a tiny PAGED pool (speculation
    enabled) under a tracer session AND an armed cost-accounting
    session, so the dump previews every snapshot section — memory
    ledger, MFU/goodput gauges, speculation counters, radix
    prefix-cache stats, cold-start report, traffic-shaping slo
    counters included — and return (snapshot, tracer). The workload
    shares an 8-token preamble so the prefix section shows a whole
    hit, a partial (pattach) hit, and misses; it runs class-tagged
    through a ShapingScheduler over a `prefill_chunk=4` pool so the
    slo section shows chunked prefills and per-class attainment."""
    import tempfile

    import numpy as np

    from paddle_tpu import nn
    from paddle_tpu.nn.layer.transformer import (TransformerDecoder,
                                                 TransformerDecoderLayer)
    from paddle_tpu.profiler import costs
    from paddle_tpu.serving import (AdapterPool, Request,
                                    ServingEngine, ShapingScheduler,
                                    session_scope)

    np.random.seed(0)
    layer = TransformerDecoderLayer(32, 2, 64, dropout=0.0)
    dec = TransformerDecoder(layer, 2)
    dec.eval()
    # a 2-tenant AdapterPool so the tenancy section renders too
    pool = AdapterPool(dec, capacity=3, rank=4)
    pool.register_random("t1", seed=1)
    pool.register_random("t2", seed=2)
    eng = ServingEngine(dec, nn.Embedding(17, 32), nn.Linear(32, 17),
                        num_slots=4, max_len=32, spec_k=4, paged=True,
                        page_size=4, num_pages=64, prefill_chunk=4,
                        adapters=pool, hbm_budget_bytes=1 << 20)
    sched = ShapingScheduler(max_queue=16, metrics=eng.metrics)
    rs = np.random.RandomState(1)
    memory = rs.randn(4, 32).astype("f4")
    pre = [0, 5, 9, 2, 11, 7, 3, 14]       # shared 8-token preamble
    prompts = [
        (pre + [6, 8], None, "batch"),     # cold CHUNKED prefill (miss)
        (pre + [6, 8], None, "interactive"),   # identical: whole hit
        (pre + [12, 4, 10], None, "batch"),    # shared 2 pages: partial
        (pre + [6, 8], "t1", "batch"),     # adapter subtree: miss
        ([0, 4, 13], "t2", "interactive"),     # unrelated: miss
        (pre + [6, 8], "t1", "batch"),     # adapter repeat: whole hit
    ]
    with costs.accounting_scope(), session_scope() as tr:
        # startup precompile into a throwaway AOT cache dir: the
        # cold_start section renders (and the serve below runs on the
        # precompiled programs — zero jit stalls, like production)
        eng.precompile((4, 32), dtype="float32",
                       prompt_buckets=(4, 16),
                       cache=tempfile.mkdtemp(prefix="pt_aot_demo_"))
        reqs = []
        for toks, name, slo in prompts:
            r = Request(np.asarray(toks, np.int32), memory,
                        max_new_tokens=int(rs.randint(2, 8)),
                        eos_id=1, adapter=name, slo=slo)
            sched.submit(r)
            reqs.append(r)
        eng.serve_until_idle(sched, max_iterations=500)
        for r in reqs:
            assert r.result(timeout=5).ok
        # snapshot INSIDE the scope so the compile-temp high-water of
        # the armed cost book lands in the memory section
        snap = eng.metrics.snapshot()
    return snap, tr


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--snapshot",
                    help="path to a json.dump'd metrics snapshot")
    ap.add_argument("--demo", action="store_true",
                    help="drive a tiny in-process pool and dump it")
    ap.add_argument("--prefix", default="paddle_tpu_serving")
    ap.add_argument("-o", "--out", help="write here instead of stdout")
    args = ap.parse_args(argv)

    from paddle_tpu.serving.metrics import to_prometheus

    tracer = None
    if args.demo:
        snap, tracer = _demo_snapshot()
    elif args.snapshot:
        with open(args.snapshot) as f:
            snap = json.load(f)
    else:
        ap.error("one of --snapshot or --demo is required")
    text = to_prometheus(snap, tracer=tracer, prefix=args.prefix)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out} ({len(text.splitlines())} lines)")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
