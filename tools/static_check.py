#!/usr/bin/env python
"""Static analyzer gate over the serving stack (paddle_tpu.analysis).

Two halves (see README "Static analysis" for the rule table):

  * AST + repo lints — lock discipline over serving/tuning/profiler
    (PTA201), snapshot()/SNAPSHOT_DOCS sync (PTA202), fault-point
    registry coverage (PTA203), np./time. in jitted bodies (PTA204);
  * program analysis — traces every program ServingEngine.precompile()
    would ready (dense / paged / sharded / spec tiny check engines,
    plus the fused optimizer step; NO compiles, trace only) and lints
    the jaxprs: baked constants (PTA101), un-donated carries (PTA102),
    float promotion (PTA103), host callbacks (PTA104), unconstrained
    sharded carries (PTA105).

Exit status is the gate: 0 when every finding has a justified entry in
the committed ANALYSIS_BASELINE.json, 1 otherwise. Stale baseline
entries (matching nothing) are reported so the allowlist only ever
ratchets DOWN — delete them, don't collect them.

Usage:

    python tools/static_check.py              # full run
    python tools/static_check.py --fast       # CI budget mode: reuse
                                              #   cached program results
                                              #   while no paddle_tpu/
                                              #   source changed
    python tools/static_check.py --json       # machine-readable report
    python tools/static_check.py --no-programs  # AST/repo lints only
    python tools/static_check.py --write-baseline  # re-seed the
                                              #   allowlist (fill in
                                              #   the justifications!)
"""
import argparse
import json
import os
import sys

# the sharded check engines need a multi-device mesh: pin the virtual
# CPU mesh BEFORE jax initializes (same workaround as tests/conftest)
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report on stdout")
    ap.add_argument("--fast", action="store_true",
                    help="reuse cached program analyses while no "
                         "paddle_tpu/ source changed (CI budget mode)")
    ap.add_argument("--no-programs", action="store_true",
                    help="skip program (jaxpr) analysis: AST + repo "
                         "lints only")
    ap.add_argument("--no-sharded", action="store_true",
                    help="skip the sharded check engines")
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default: repo "
                         "ANALYSIS_BASELINE.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write every current finding into the "
                         "baseline (justification left as TODO) "
                         "instead of gating")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import Baseline, render_text, runner

    report = runner.run(
        programs=not args.no_programs,
        include_sharded=not args.no_sharded,
        fast=args.fast,
        baseline_path=args.baseline)

    if args.write_baseline:
        path = args.baseline or os.path.join(runner.repo_root(),
                                             runner.BASELINE_NAME)
        entries = []
        seen = set()
        for f in report["findings"]:
            k = (f.rule, f.baseline_key)
            if k in seen:
                continue
            seen.add(k)
            entries.append({"rule": f.rule, "match": f.baseline_key,
                            "justification": "TODO: justify or fix"})
        Baseline(entries).save(path)
        print(f"wrote {len(entries)} baseline entries to {path} — "
              f"replace every TODO justification before committing")
        return 0

    if args.json:
        json.dump({
            "ok": report["ok"],
            "cache": report["cache"],
            "findings": [f.as_dict() for f in report["findings"]],
            "new": [f.as_dict() for f in report["new"]],
            "baselined": [f.as_dict() for f in report["baselined"]],
            "stale_baseline": report["stale_baseline"],
        }, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        n = len(report["findings"])
        print(f"static_check: {n} finding(s) — "
              f"{len(report['baselined'])} baselined (justified), "
              f"{len(report['new'])} new"
              + (f"  [program cache {report['cache']}]"
                 if report["cache"] else ""))
        if report["new"]:
            print("NEW findings (fix, or baseline with a "
                  "justification):")
            print(render_text(report["new"]))
        for e in report["stale_baseline"]:
            print(f"stale baseline entry (matches nothing — delete "
                  f"it): {e['rule']} {e['match']!r}")
        print("PASS" if report["ok"] else "FAIL")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
