#!/usr/bin/env python
"""Per-request latency waterfalls from a serving chrome-trace file.

Reads a trace exported by `Tracer.export_chrome_trace` (the artifact
`bench.py serving_* --trace`, `tools/chaos_check.py --trace`, or any
`paddle_tpu.serving.session_scope()` run writes) and renders the
per-request breakdown: queue / join(prefill) / pending-splice / decode
phase totals with p50/p95 across requests, plus the slowest requests
as ASCII waterfalls. The same trace loads graphically in Perfetto
(ui.perfetto.dev) — this is the terminal view.

    python tools/trace_report.py /tmp/trace.json [--top 10]
    python tools/trace_report.py trace.json --percentiles 50,95,99
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="chrome-trace JSON file")
    ap.add_argument("--percentiles", default="50,95",
                    help="comma-separated percentiles for the phase "
                         "table")
    ap.add_argument("--top", type=int, default=8,
                    help="render the N slowest requests as waterfalls "
                         "(0 = table only)")
    ap.add_argument("--incomplete", action="store_true",
                    help="also list requests whose waterfall is "
                         "incomplete (missing queue/join/terminal)")
    args = ap.parse_args(argv)

    # pure-stdlib + numpy path: no jax import needed to read a trace
    from paddle_tpu.serving.tracing import (load_chrome_trace,
                                            waterfall_report, waterfalls)

    events = load_chrome_trace(args.trace)
    pcts = tuple(float(q) for q in args.percentiles.split(","))
    print(waterfall_report(events, percentiles=pcts, top=args.top))
    if args.incomplete:
        wf = waterfalls(events)
        bad = {tid: w for tid, w in wf.items() if not w["complete"]}
        if bad:
            print(f"\nincomplete waterfalls ({len(bad)}):")
            for tid, w in sorted(bad.items()):
                have = sorted({e["name"] for e in w["spans"]})
                print(f"  req {tid}: spans={have} reason={w['reason']}")
        else:
            print("\nall waterfalls complete")
    # engine-track quick stats
    compiles = [e for e in events if e.get("name") == "compile"]
    steps = [e for e in events if e.get("name") == "decode.step"]
    retraces = [e for e in events if e.get("name") == "retrace"]
    if compiles:
        total_ms = sum(e.get("dur", 0) for e in compiles) / 1e3
        print(f"\ncompiles: {len(compiles)} "
              f"({total_ms:.1f}ms total compile wall)")
        for e in compiles:
            print(f"  {e['args'].get('key')}  "
                  f"{e.get('dur', 0) / 1e3:9.1f}ms  "
                  f"count={e['args'].get('count')}")
    pre = [e for e in events if e.get("name") == "precompile"]
    if pre:
        hits = sum(1 for e in pre if e["args"].get("source") == "cache")
        total_ms = sum(e.get("dur", 0) for e in pre) / 1e3
        print(f"startup precompile: {len(pre)} programs "
              f"({hits} from cache, {len(pre) - hits} compiled; "
              f"{total_ms:.1f}ms wall)")
    if steps:
        print(f"decode steps: {len(steps)}")
    drafts = [e for e in events if e.get("name") == "decode.draft"]
    verifies = [e for e in events if e.get("name") == "decode.verify"]
    if verifies:
        acc = sum(e["args"].get("accepted", 0) for e in verifies)
        prop = sum(e["args"].get("proposed", 0) for e in verifies)
        d_ms = sum(e.get("dur", 0) for e in drafts) / 1e3
        v_ms = sum(e.get("dur", 0) for e in verifies) / 1e3
        print(f"speculation: {len(verifies)} draft/verify pairs, "
              f"acceptance {acc}/{prop} ({acc / max(1, prop):.0%}), "
              f"draft {d_ms:.1f}ms + verify {v_ms:.1f}ms wall")
    if retraces:
        print(f"RETRACE VIOLATIONS: {len(retraces)}")
        for e in retraces:
            print(f"  {e['args']}")
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:            # e.g. piped into head
        sys.exit(0)
