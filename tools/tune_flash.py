#!/usr/bin/env python
"""Flash-attention block-size sweep on the attached TPU chip.

Measures fwd+bwd (causal bf16) per-step time for (block_q, block_k)
combinations with bench.py's two-point marginal methodology, against the
XLA fused reference. Writes the winners to stdout; _pick_blocks in
ops/attention.py encodes the result as a static table.

Usage: python tools/tune_flash.py [--seqs 1024,2048,4096] [--iters N]
Run STRICTLY alone on the chip (two jax processes contend on the tunnel).
"""
import argparse
import functools
import sys
import time

sys.path.insert(0, ".")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="1024,2048,4096")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--b", type=int, default=2)
    ap.add_argument("--h", type=int, default=16)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--dropout", type=float, default=0.0)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench
    from paddle_tpu.ops import attention as att

    assert att._flash_usable(), "flash probe failed on this backend"

    iters_by_seq = {1024: 256, 2048: 96, 4096: 32}
    seed = jnp.array([1234], jnp.int32)

    for S in [int(s) for s in args.seqs.split(",")]:
        n_it = args.iters or iters_by_seq.get(S, 48)
        q = jnp.asarray(np.random.RandomState(0).randn(
            args.b, args.h, S, args.d), jnp.bfloat16)

        def timeit(fn):
            def loss(q, k, v):
                return fn(q, k, v).astype(jnp.float32).sum()

            g = jax.grad(loss, (0, 1, 2))

            @functools.partial(jax.jit, static_argnums=3)
            def run_n(q, k, v, n):
                def body(c, _):
                    qp = (q * (1 + c * 1e-9)).astype(q.dtype)
                    gq, gk, gv = g(qp, k, v)
                    return gq.astype(jnp.float32).mean(), None
                c, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=n)
                return c

            def timed(n):
                t0 = time.perf_counter()
                r = float(run_n(q, q, q, n))
                assert r == r
                return time.perf_counter() - t0

            dt, _, _ = bench._marginal_step_time(timed, n_it, lo_frac=4)
            return dt * 1e3

        t_ref = timeit(lambda q, k, v: att.sdpa_reference(
            q, k, v, None, True, None))
        print(f"seq{S}: xla_ref {t_ref:.3f} ms")
        results = {}
        for bq in (128, 256, 512, 1024):
            for bk in (128, 256, 512, 1024):
                if bq > S or bk > S:
                    continue
                try:
                    t = timeit(lambda q, k, v, bq=bq, bk=bk:
                               att.flash_attention(
                                   q, k, v, None, True, None,
                                   block_q=bq, block_k=bk,
                                   dropout_p=args.dropout,
                                   dropout_seed=(seed if args.dropout
                                                 else None)))
                    results[(bq, bk)] = t
                    print(f"  bq{bq} bk{bk}: {t:.3f} ms "
                          f"({t_ref / t:.3f}x vs ref)")
                except Exception as e:
                    print(f"  bq{bq} bk{bk}: FAIL {type(e).__name__}")
        best = min(results, key=results.get)
        print(f"seq{S} BEST: bq{best[0]} bk{best[1]} = "
              f"{results[best]:.3f} ms ({t_ref / results[best]:.3f}x)")


if __name__ == "__main__":
    main()
