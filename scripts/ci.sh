#!/usr/bin/env bash
# CI smoke for paddle_tpu (paddle/scripts/paddle_build.sh role, compact):
#   1. full test suite on the virtual-CPU mesh
#   2. quick per-op micro-benchmarks, compared against the committed
#      OP_BENCH.json baseline (>2x step-time regressions fail the run
#      only with CI_STRICT_PERF=1; they always print)
#   3. bench.py CPU dry-run of the CTR config (exercises the native PS)
# Usage: scripts/ci.sh [pytest-args...]
set -u -o pipefail
cd "$(dirname "$0")/.."

rc=0

echo "== [1/3] pytest =="
python -m pytest tests/ -q -x "$@" || rc=1

echo "== [1b] README bench-claim hygiene =="
python tools/check_readme_bench.py || rc=1

echo "== [1c] static analyzer gate (AST lints + cached program analyses) =="
if python tools/static_check.py --fast --json > /tmp/static_check.json; then
  echo "static-check: pass (see /tmp/static_check.json)"
else
  echo "static-check: NEW findings (see /tmp/static_check.json; fix or justify in ANALYSIS_BASELINE.json)"
  rc=1
fi

echo "== [2/3] op micro-bench (quick, vs baseline) =="
if python tools/op_bench.py --cpu --quick --compare; then
  echo "op-bench: no >2x regressions"
else
  echo "op-bench: regressions detected (see above)"
  if [ "${CI_STRICT_PERF:-0}" = "1" ]; then rc=1; fi
fi

echo "== [2b] perf gate (quick 2-row smoke vs committed baselines) =="
if python tools/perf_gate.py --cpu --quick --out /tmp/PERF_GATE.json; then
  echo "perf-gate: pass (see /tmp/PERF_GATE.json)"
else
  echo "perf-gate: regressions/missing rows detected (see above)"
  rc=1
fi

echo "== [2c] kernel autotune smoke sweep (dry-run, mechanics only) =="
if python tools/autotune.py --cpu --smoke --dry-run > /tmp/autotune_smoke.json; then
  echo "autotune: smoke sweep ok (see /tmp/autotune_smoke.json)"
else
  echo "autotune: smoke sweep FAILED"
  rc=1
fi

echo "== [3/3] bench dry-run (ctr_ps, small, cpu) =="
JAX_PLATFORMS=cpu python - <<'PY' || rc=1
import _cpu_debug  # noqa: F401
import bench

r = bench._ctr_dnn_ps(batch=256, chunks=2, merge_k=2)
assert "value" in r, r
print("ctr dry-run ok:", r["value"], r["unit"])
PY

exit $rc
