// Out-of-core MultiSlot DataFeed.
//
// Capability parity with the reference's framework/data_feed.h
// (MultiSlotDataFeed / MultiSlotInMemoryDataFeed) + data_set.h
// (InMemoryDataset shuffle) — re-designed: N parser threads stream text
// files through a bounded record queue; an assembler thread builds
// ragged batches (values + LoD offsets per slot) that the host hands to
// XLA as padded/segment inputs.
//
// Text format (one sample per line, slots in declared order):
//   <n> v1 ... vn  <m> u1 ... um  ...
// i.e. each slot is a count followed by that many values (float or int64),
// the same MultiSlot wire format the reference ingests.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "blocking_queue.h"

namespace ptcore {

struct SlotConf {
  std::string name;
  bool is_float = true;  // else int64
  int dense_dim = -1;    // >0: fixed-size slot (validated); -1: ragged
};

// One sample: per-slot ragged values.
struct Record {
  std::vector<std::vector<float>> fvals;    // parallel to float slots order
  std::vector<std::vector<int64_t>> ivals;  // parallel to int slots order
};

// One assembled batch, ready for zero-copy export through the C API.
struct Batch {
  // per slot: flattened values + offsets (batch_size+1 entries).
  std::vector<std::vector<float>> fvals;
  std::vector<std::vector<int64_t>> ivals;
  std::vector<std::vector<int64_t>> offsets;  // per slot
  int64_t batch_size = 0;
};

class DataFeed {
 public:
  DataFeed(std::vector<SlotConf> slots, int num_threads, size_t queue_cap);
  ~DataFeed();

  void AddFile(const std::string& path);
  // shuffle_buf > 0 enables reservoir-style streaming shuffle.
  void Start(int batch_size, int64_t shuffle_buf, uint64_t seed);
  // Blocks; returns nullptr at end of epoch.
  std::unique_ptr<Batch> Next();
  void Stop();

  const std::vector<SlotConf>& slots() const { return slots_; }
  int64_t samples_seen() const { return samples_seen_.load(); }
  // first-error-wins, written once under err_mu_; the acquire load pairs
  // with SetError's release store so readers never observe a half-written
  // string (parser threads race to report; pt_feed_error reads concurrently)
  const std::string& error() const {
    static const std::string kEmpty;
    return has_error_.load(std::memory_order_acquire) ? error_ : kEmpty;
  }
  void SetError(std::string msg);

 private:
  void ParseWorker();
  void AssembleWorker(int batch_size, int64_t shuffle_buf, uint64_t seed);
  bool ParseLine(const char* p, size_t len, Record* rec);
  bool ParseBinaryFile(FILE* f, const std::string& path);

  std::vector<SlotConf> slots_;
  int nf_ = 0, ni_ = 0;  // float/int slot counts
  int num_threads_;
  std::vector<std::string> files_;
  BlockingQueue<std::string> file_q_;
  BlockingQueue<Record> record_q_;
  BlockingQueue<std::unique_ptr<Batch>> batch_q_;
  std::vector<std::thread> parsers_;
  std::thread assembler_;
  std::atomic<int> live_parsers_{0};
  std::atomic<int64_t> samples_seen_{0};
  std::mutex err_mu_;
  std::atomic<bool> has_error_{false};
  std::string error_;
  bool started_ = false;
};

}  // namespace ptcore
