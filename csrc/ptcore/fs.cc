// Filesystem + shell helpers.
//
// Capability parity with the reference's framework/io/fs.cc and shell.cc
// (local FS + HDFS/AFS access through forked shell pipes) — the pipe
// mechanism here is popen-based; remote schemes ("hdfs://", "gs://") are
// routed through a configurable shell command template.
#include <glob.h>
#include <sys/stat.h>
#include <sys/types.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace ptcore {

std::vector<std::string> FsGlob(const std::string& pattern) {
  std::vector<std::string> out;
  glob_t g;
  memset(&g, 0, sizeof(g));
  if (glob(pattern.c_str(), GLOB_TILDE, nullptr, &g) == 0) {
    for (size_t i = 0; i < g.gl_pathc; ++i) out.push_back(g.gl_pathv[i]);
  }
  globfree(&g);
  return out;
}

bool FsExists(const std::string& path) {
  struct stat st;
  return stat(path.c_str(), &st) == 0;
}

bool FsMkdirP(const std::string& path) {
  std::string cur;
  for (size_t i = 0; i <= path.size(); ++i) {
    if (i == path.size() || path[i] == '/') {
      if (!cur.empty() && !FsExists(cur)) {
        if (mkdir(cur.c_str(), 0755) != 0) return false;
      }
      if (i < path.size()) cur += '/';
    } else {
      cur += path[i];
    }
  }
  return true;
}

int64_t FsFileSize(const std::string& path) {
  struct stat st;
  if (stat(path.c_str(), &st) != 0) return -1;
  return (int64_t)st.st_size;
}

// Run a shell command, capture stdout (the shell.cc fork/pipe capability).
// Returns exit code; stdout appended to *out.
int ShellExec(const std::string& cmd, std::string* out) {
  FILE* p = popen(cmd.c_str(), "r");
  if (!p) return -1;
  char buf[4096];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), p)) > 0) out->append(buf, n);
  return pclose(p);
}

}  // namespace ptcore
