// Model encryption: AES-128-CTR with an HMAC-ish integrity tag.
//
// Reference parity: paddle/fluid/framework/io/crypto/ (AES cipher over
// cryptopp) + pybind/crypto.cc. This is a from-scratch AES-128
// implementation (FIPS-197 tables) in CTR mode — encrypt == decrypt, no
// padding — suitable for encrypting __model__/__params__ artifacts at
// rest. Key derivation from a passphrase uses iterated FNV-1a-based
// mixing (models-at-rest obfuscation parity with the reference's
// key-file scheme, not a general-purpose KDF).
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <random>
#include <string>
#include <vector>

namespace ptcrypto {

static const uint8_t SBOX[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67,
    0x2b, 0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59,
    0x47, 0xf0, 0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7,
    0xfd, 0x93, 0x26, 0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1,
    0x71, 0xd8, 0x31, 0x15, 0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05,
    0x9a, 0x07, 0x12, 0x80, 0xe2, 0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83,
    0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0, 0x52, 0x3b, 0xd6, 0xb3, 0x29,
    0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed, 0x20, 0xfc, 0xb1, 0x5b,
    0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf, 0xd0, 0xef, 0xaa,
    0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f, 0x50, 0x3c,
    0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5, 0xbc,
    0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19,
    0x73, 0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee,
    0xb8, 0x14, 0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49,
    0x06, 0x24, 0x5c, 0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79,
    0xe7, 0xc8, 0x37, 0x6d, 0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4,
    0xea, 0x65, 0x7a, 0xae, 0x08, 0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6,
    0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f, 0x4b, 0xbd, 0x8b, 0x8a, 0x70,
    0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e, 0x61, 0x35, 0x57, 0xb9,
    0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11, 0x69, 0xd9, 0x8e,
    0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf, 0x8c, 0xa1,
    0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f, 0xb0,
    0x54, 0xbb, 0x16};

static const uint8_t RCON[11] = {0x00, 0x01, 0x02, 0x04, 0x08, 0x10,
                                 0x20, 0x40, 0x80, 0x1b, 0x36};

struct Aes128 {
  uint8_t rk[176];  // 11 round keys

  explicit Aes128(const uint8_t key[16]) {
    std::memcpy(rk, key, 16);
    for (int i = 4; i < 44; ++i) {
      uint8_t t[4];
      std::memcpy(t, rk + 4 * (i - 1), 4);
      if (i % 4 == 0) {
        uint8_t tmp = t[0];
        t[0] = static_cast<uint8_t>(SBOX[t[1]] ^ RCON[i / 4]);
        t[1] = SBOX[t[2]];
        t[2] = SBOX[t[3]];
        t[3] = SBOX[tmp];
      }
      for (int j = 0; j < 4; ++j)
        rk[4 * i + j] = rk[4 * (i - 4) + j] ^ t[j];
    }
  }

  static uint8_t xtime(uint8_t x) {
    return static_cast<uint8_t>((x << 1) ^ ((x >> 7) * 0x1b));
  }

  void EncryptBlock(const uint8_t in[16], uint8_t out[16]) const {
    uint8_t s[16];
    for (int i = 0; i < 16; ++i) s[i] = in[i] ^ rk[i];
    for (int round = 1; round <= 10; ++round) {
      uint8_t t[16];
      // SubBytes + ShiftRows
      for (int c = 0; c < 4; ++c)
        for (int r = 0; r < 4; ++r)
          t[4 * c + r] = SBOX[s[4 * ((c + r) % 4) + r]];
      if (round < 10) {
        // MixColumns
        for (int c = 0; c < 4; ++c) {
          uint8_t a0 = t[4 * c], a1 = t[4 * c + 1], a2 = t[4 * c + 2],
                  a3 = t[4 * c + 3];
          s[4 * c] = static_cast<uint8_t>(xtime(a0) ^ xtime(a1) ^ a1 ^
                                          a2 ^ a3);
          s[4 * c + 1] = static_cast<uint8_t>(a0 ^ xtime(a1) ^
                                              xtime(a2) ^ a2 ^ a3);
          s[4 * c + 2] = static_cast<uint8_t>(a0 ^ a1 ^ xtime(a2) ^
                                              xtime(a3) ^ a3);
          s[4 * c + 3] = static_cast<uint8_t>(xtime(a0) ^ a0 ^ a1 ^ a2 ^
                                              xtime(a3));
        }
      } else {
        std::memcpy(s, t, 16);
      }
      for (int i = 0; i < 16; ++i) s[i] ^= rk[16 * round + i];
    }
    std::memcpy(out, s, 16);
  }
};

// CTR keystream transform (in place); iv = 16-byte counter block.
static void CtrTransform(const Aes128& aes, const uint8_t iv[16],
                         uint8_t* data, size_t n) {
  uint8_t ctr[16], ks[16];
  std::memcpy(ctr, iv, 16);
  for (size_t off = 0; off < n; off += 16) {
    aes.EncryptBlock(ctr, ks);
    size_t chunk = n - off < 16 ? n - off : 16;
    for (size_t i = 0; i < chunk; ++i) data[off + i] ^= ks[i];
    for (int i = 15; i >= 0; --i)  // big-endian counter increment
      if (++ctr[i] != 0) break;
  }
}

// passphrase -> 16-byte key (iterated 64-bit FNV-1a mixing)
static void DeriveKey(const char* pass, uint8_t key[16]) {
  uint64_t h1 = 1469598103934665603ULL, h2 = 1099511628211ULL ^ 0x5bd1e995;
  size_t n = std::strlen(pass);
  for (int iter = 0; iter < 1024; ++iter) {
    for (size_t i = 0; i < n; ++i) {
      h1 = (h1 ^ static_cast<uint8_t>(pass[i])) * 1099511628211ULL;
      h2 = (h2 ^ h1) * 0x100000001b3ULL + iter;
    }
    h1 ^= h2 >> 13;
    h2 ^= h1 << 7;
  }
  std::memcpy(key, &h1, 8);
  std::memcpy(key + 8, &h2, 8);
}

static uint64_t Fnv(const uint8_t* p, size_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (size_t i = 0; i < n; ++i) h = (h ^ p[i]) * 1099511628211ULL;
  return h;
}

// KEYED tag (NOT a cryptographic MAC — tamper-evidence for operational
// integrity, parity with the reference's checksum role): absorbs
// key || iv || data || key so (a) the random IV decorrelates equal
// plaintexts and (b) the trailing key absorption blocks running the
// absorption backwards from a known plaintext.
static uint64_t KeyedTag(const uint8_t key[16], const uint8_t iv[16],
                         const uint8_t* p, size_t n) {
  uint64_t h = Fnv(key, 16);
  for (size_t i = 0; i < 16; ++i) h = (h ^ iv[i]) * 1099511628211ULL;
  for (size_t i = 0; i < n; ++i) h = (h ^ p[i]) * 1099511628211ULL;
  for (size_t i = 0; i < 16; ++i) h = (h ^ key[i]) * 1099511628211ULL;
  h ^= h >> 30; h *= 0xbf58476d1ce4e5b9ULL;
  h ^= h >> 27; h *= 0x94d049bb133111ebULL;
  return h ^ (h >> 31);
}

}  // namespace ptcrypto

static const char kMagic[8] = {'P', 'T', 'E', 'N', 'C', '1', 0, 0};

extern "C" {

// Encrypt src file into dst: [magic 8][iv 16][tag 8][ciphertext].
int pt_cipher_encrypt_file(const char* src, const char* dst,
                           const char* passphrase) {
  FILE* fi = std::fopen(src, "rb");
  if (!fi) return -1;
  std::fseek(fi, 0, SEEK_END);
  long n = std::ftell(fi);
  std::fseek(fi, 0, SEEK_SET);
  std::vector<uint8_t> buf(n > 0 ? n : 0);
  if (n > 0 && std::fread(buf.data(), 1, n, fi) != (size_t)n) {
    std::fclose(fi);
    return -2;
  }
  std::fclose(fi);

  uint8_t key[16];
  ptcrypto::DeriveKey(passphrase, key);
  ptcrypto::Aes128 aes(key);
  // RANDOM IV: identical plaintexts encrypt to unrelated ciphertexts
  uint8_t iv[16];
  {
    std::random_device rd;
    for (int i = 0; i < 16; i += 4) {
      uint32_t r = rd();
      std::memcpy(iv + i, &r, 4);
    }
  }
  uint64_t tag = ptcrypto::KeyedTag(key, iv, buf.data(), buf.size());

  ptcrypto::CtrTransform(aes, iv, buf.data(), buf.size());

  FILE* fo = std::fopen(dst, "wb");
  if (!fo) return -3;
  size_t wrote = std::fwrite(kMagic, 1, 8, fo);
  wrote += std::fwrite(iv, 1, 16, fo);
  wrote += std::fwrite(&tag, 1, 8, fo);
  if (!buf.empty()) wrote += std::fwrite(buf.data(), 1, buf.size(), fo);
  int rc = std::fclose(fo);
  if (wrote != 32 + buf.size() || rc != 0) return -6;  // short write
  return 0;
}

// Decrypt dst of pt_cipher_encrypt_file. Returns 0 ok, -4 wrong format,
// -5 wrong passphrase / corrupted (integrity tag mismatch).
int pt_cipher_decrypt_file(const char* src, const char* dst,
                           const char* passphrase) {
  FILE* fi = std::fopen(src, "rb");
  if (!fi) return -1;
  char magic[8];
  uint8_t iv[16];
  uint64_t tag = 0;
  if (std::fread(magic, 1, 8, fi) != 8 ||
      std::memcmp(magic, kMagic, 8) != 0 ||
      std::fread(iv, 1, 16, fi) != 16 ||
      std::fread(&tag, 1, 8, fi) != 8) {
    std::fclose(fi);
    return -4;
  }
  std::fseek(fi, 0, SEEK_END);
  long total = std::ftell(fi);
  long n = total - 32;
  std::fseek(fi, 32, SEEK_SET);
  std::vector<uint8_t> buf(n > 0 ? n : 0);
  if (n > 0 && std::fread(buf.data(), 1, n, fi) != (size_t)n) {
    std::fclose(fi);
    return -2;
  }
  std::fclose(fi);

  uint8_t key[16];
  ptcrypto::DeriveKey(passphrase, key);
  ptcrypto::Aes128 aes(key);
  ptcrypto::CtrTransform(aes, iv, buf.data(), buf.size());
  if (ptcrypto::KeyedTag(key, iv, buf.data(), buf.size()) != tag)
    return -5;

  FILE* fo = std::fopen(dst, "wb");
  if (!fo) return -3;
  size_t wrote = buf.empty() ? 0
      : std::fwrite(buf.data(), 1, buf.size(), fo);
  int rc = std::fclose(fo);
  if (wrote != buf.size() || rc != 0) return -6;
  return 0;
}

int pt_cipher_is_encrypted(const char* path) {
  FILE* fi = std::fopen(path, "rb");
  if (!fi) return 0;
  char magic[8];
  size_t got = std::fread(magic, 1, 8, fi);
  std::fclose(fi);
  return got == 8 && std::memcmp(magic, kMagic, 8) == 0 ? 1 : 0;
}

}  // extern "C"
