// extern "C" surface for ctypes (paddle_tpu/core/native.py).
//
// The reference exposes its native runtime through pybind11
// (paddle/fluid/pybind/pybind.cc); here the binding layer is a flat C ABI
// so no build-time Python dependency exists — the Python side wraps these
// with ctypes and numpy zero-copy views.
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "arena.h"
#include "datafeed.h"
#include "saveload.h"

namespace ptcore {
// fs.cc
std::vector<std::string> FsGlob(const std::string&);
bool FsExists(const std::string&);
bool FsMkdirP(const std::string&);
int64_t FsFileSize(const std::string&);
int ShellExec(const std::string&, std::string*);
}  // namespace ptcore

using namespace ptcore;

extern "C" {

// ---------- version ----------
const char* pt_version() { return "ptcore-0.1"; }

// ---------- arena ----------
void* pt_arena_create(uint64_t chunk_bytes) {
  return new Arena(chunk_bytes ? chunk_bytes : (64u << 20));
}
void pt_arena_destroy(void* a) { delete (Arena*)a; }
void* pt_arena_alloc(void* a, uint64_t n) { return ((Arena*)a)->Alloc(n); }
void pt_arena_free(void* a, void* p) { ((Arena*)a)->Free(p); }
uint64_t pt_arena_in_use(void* a) { return ((Arena*)a)->InUse(); }
uint64_t pt_arena_peak(void* a) { return ((Arena*)a)->Peak(); }
uint64_t pt_arena_reserved(void* a) { return ((Arena*)a)->Reserved(); }

// ---------- datafeed ----------
// slot spec strings: name, is_float (0/1), dense_dim
void* pt_feed_create(int nslots, const char** names, const int* is_float,
                     const int* dense_dim, int num_threads) {
  std::vector<SlotConf> slots;
  for (int i = 0; i < nslots; ++i)
    slots.push_back(SlotConf{names[i], is_float[i] != 0, dense_dim[i]});
  return new DataFeed(std::move(slots), num_threads, 4096);
}
void pt_feed_destroy(void* h) { delete (DataFeed*)h; }
void pt_feed_add_file(void* h, const char* path) {
  ((DataFeed*)h)->AddFile(path);
}
void pt_feed_start(void* h, int batch_size, int64_t shuffle_buf,
                   uint64_t seed) {
  ((DataFeed*)h)->Start(batch_size, shuffle_buf, seed);
}
void pt_feed_stop(void* h) { ((DataFeed*)h)->Stop(); }
int64_t pt_feed_samples_seen(void* h) {
  return ((DataFeed*)h)->samples_seen();
}
const char* pt_feed_error(void* h) { return ((DataFeed*)h)->error().c_str(); }

// Pops the next batch; returns an opaque Batch* or NULL at epoch end.
void* pt_feed_next(void* h) { return ((DataFeed*)h)->Next().release(); }
void pt_batch_destroy(void* b) { delete (Batch*)b; }
int64_t pt_batch_size(void* b) { return ((Batch*)b)->batch_size; }
// Per-slot accessors. slot_idx follows the feed's declared slot order;
// fslot/islot index within float/int slots respectively.
int64_t pt_batch_values_len(void* bp, int is_float, int sub_idx) {
  Batch* b = (Batch*)bp;
  return is_float ? (int64_t)b->fvals[sub_idx].size()
                  : (int64_t)b->ivals[sub_idx].size();
}
void pt_batch_copy_fvalues(void* bp, int sub_idx, float* out) {
  Batch* b = (Batch*)bp;
  memcpy(out, b->fvals[sub_idx].data(), b->fvals[sub_idx].size() * 4);
}
void pt_batch_copy_ivalues(void* bp, int sub_idx, int64_t* out) {
  Batch* b = (Batch*)bp;
  memcpy(out, b->ivals[sub_idx].data(), b->ivals[sub_idx].size() * 8);
}
void pt_batch_copy_offsets(void* bp, int slot_idx, int64_t* out) {
  Batch* b = (Batch*)bp;
  memcpy(out, b->offsets[slot_idx].data(), b->offsets[slot_idx].size() * 8);
}

// ---------- save/load ----------
int pt_save_tensor(const char* path, uint8_t dtype, const int64_t* dims,
                   int ndim, const void* data, uint64_t nbytes) {
  return SaveTensorFile(path, dtype, dims, ndim, data, nbytes) ? 0 : -1;
}
void* pt_load_tensor(const char* path) {
  auto* t = new HostTensor;
  if (!LoadTensorFile(path, t)) {
    delete t;
    return nullptr;
  }
  return t;
}
uint8_t pt_tensor_dtype(void* t) { return ((HostTensor*)t)->dtype; }
int pt_tensor_ndim(void* t) { return (int)((HostTensor*)t)->dims.size(); }
void pt_tensor_dims(void* t, int64_t* out) {
  auto* ht = (HostTensor*)t;
  memcpy(out, ht->dims.data(), ht->dims.size() * 8);
}
uint64_t pt_tensor_nbytes(void* t) {
  return (uint64_t)((HostTensor*)t)->data.size();
}
void pt_tensor_copy_data(void* t, void* out) {
  auto* ht = (HostTensor*)t;
  memcpy(out, ht->data.data(), ht->data.size());
}
void pt_tensor_destroy(void* t) { delete (HostTensor*)t; }

void* pt_combine_open(const char* path) { return CombineOpen(path); }
int pt_combine_add(void* w, const char* name, uint8_t dtype,
                   const int64_t* dims, int ndim, const void* data,
                   uint64_t nbytes) {
  return CombineAdd((CombineWriter*)w, name, dtype, dims, ndim, data, nbytes)
             ? 0
             : -1;
}
int pt_combine_close(void* w) {
  return CombineClose((CombineWriter*)w) ? 0 : -1;
}
void* pt_combine_load(const char* path) { return CombineLoad(path); }
int pt_combine_complete(void* r) {
  return ((CombineReader*)r)->complete ? 1 : 0;
}
int pt_combine_count(void* r) {
  return (int)((CombineReader*)r)->entries.size();
}
const char* pt_combine_name(void* r, int i) {
  return ((CombineReader*)r)->entries[i].first.c_str();
}
void* pt_combine_tensor(void* r, int i) {
  return &((CombineReader*)r)->entries[i].second;
}
void pt_combine_destroy(void* r) { delete (CombineReader*)r; }

// ---------- fs / shell ----------
// Glob: returns count; results retrieved one by one via a thread-local
// scratch (simple, adequate for a binding layer).
static thread_local std::vector<std::string> g_glob;
int pt_fs_glob(const char* pattern) {
  g_glob = FsGlob(pattern);
  return (int)g_glob.size();
}
const char* pt_fs_glob_get(int i) { return g_glob[(size_t)i].c_str(); }
int pt_fs_exists(const char* p) { return FsExists(p) ? 1 : 0; }
int pt_fs_mkdir_p(const char* p) { return FsMkdirP(p) ? 0 : -1; }
int64_t pt_fs_file_size(const char* p) { return FsFileSize(p); }
static thread_local std::string g_shell_out;
int pt_shell_exec(const char* cmd) {
  g_shell_out.clear();
  return ShellExec(cmd, &g_shell_out);
}
const char* pt_shell_output() { return g_shell_out.c_str(); }

// ---------- profiler ----------
void pt_prof_enable();
void pt_prof_disable();
int pt_prof_enabled();
uint64_t pt_prof_now_ns();
void pt_prof_record(const char* name, uint64_t start_ns, uint64_t end_ns);
int pt_prof_dump(const char* path);
void pt_prof_clear();
uint64_t pt_prof_count();

}  // extern "C"
