// Bounded MPMC blocking queue — the native backbone of the DataFeed
// pipeline. Capability parity with the reference's
// operators/reader/lod_tensor_blocking_queue and framework/blocking_queue.h,
// designed fresh (condition-variable ring, close semantics).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <utility>

namespace ptcore {

template <typename T>
class BlockingQueue {
 public:
  explicit BlockingQueue(size_t capacity = 64) : cap_(capacity) {}

  // Returns false iff the queue was closed.
  bool Push(T&& v) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || closed_; });
    if (closed_) return false;
    q_.push_back(std::move(v));
    not_empty_.notify_one();
    return true;
  }

  // Returns false iff closed AND drained.
  bool Pop(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || closed_; });
    if (q_.empty()) return false;
    *out = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  void Reopen() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = false;
    q_.clear();
  }

  size_t Size() const {
    std::lock_guard<std::mutex> lk(mu_);
    return q_.size();
  }

  bool Closed() const {
    std::lock_guard<std::mutex> lk(mu_);
    return closed_;
  }

 private:
  mutable std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<T> q_;
  size_t cap_;
  bool closed_ = false;
};

}  // namespace ptcore
