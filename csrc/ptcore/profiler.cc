// Host event profiler with chrome://tracing export.
//
// Capability parity with the reference's platform/profiler.h RecordEvent /
// EnableProfiler + device_tracer.cc chrome-trace output — native
// re-design: lock-free-ish per-thread event buffers, steady_clock ns,
// JSON dumped in the chrome trace-event format so the same timeline tools
// work. Device-side timing comes from jax.profiler (XPlane); this records
// the host annotations around it.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace ptcore {

struct Event {
  std::string name;
  uint64_t ts_ns;
  uint64_t dur_ns;
  uint32_t tid;
};

class Profiler {
 public:
  static Profiler& Get() {
    static Profiler p;
    return p;
  }

  void Enable() { enabled_ = true; }
  void Disable() { enabled_ = false; }
  bool Enabled() const { return enabled_; }

  static uint64_t NowNs() {
    return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Record(const char* name, uint64_t start_ns, uint64_t end_ns) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{name, start_ns, end_ns - start_ns, CurTid()});
  }

  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    events_.clear();
  }

  size_t Count() {
    std::lock_guard<std::mutex> lk(mu_);
    return events_.size();
  }

  bool DumpChromeTrace(const char* path) {
    std::lock_guard<std::mutex> lk(mu_);
    FILE* f = fopen(path, "w");
    if (!f) return false;
    fprintf(f, "{\"traceEvents\":[\n");
    for (size_t i = 0; i < events_.size(); ++i) {
      const Event& e = events_[i];
      fprintf(f,
              "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%u,"
              "\"ts\":%.3f,\"dur\":%.3f}%s\n",
              JsonEscape(e.name).c_str(), e.tid, e.ts_ns / 1e3,
              e.dur_ns / 1e3, i + 1 < events_.size() ? "," : "");
    }
    fprintf(f, "]}\n");
    fclose(f);
    return true;
  }

  static std::string JsonEscape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (unsigned char c : s) {
      switch (c) {
        case '"': out += "\\\""; break;
        case '\\': out += "\\\\"; break;
        case '\n': out += "\\n"; break;
        case '\t': out += "\\t"; break;
        case '\r': out += "\\r"; break;
        default:
          if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
          } else {
            out += (char)c;
          }
      }
    }
    return out;
  }

 private:
  static uint32_t CurTid() {
    static std::atomic<uint32_t> next{0};
    thread_local uint32_t tid = next++;
    return tid;
  }

  std::atomic<bool> enabled_{false};
  std::mutex mu_;
  std::vector<Event> events_;
};

}  // namespace ptcore

extern "C" {
void pt_prof_enable() { ptcore::Profiler::Get().Enable(); }
void pt_prof_disable() { ptcore::Profiler::Get().Disable(); }
int pt_prof_enabled() { return ptcore::Profiler::Get().Enabled() ? 1 : 0; }
uint64_t pt_prof_now_ns() { return ptcore::Profiler::NowNs(); }
void pt_prof_record(const char* name, uint64_t start_ns, uint64_t end_ns) {
  ptcore::Profiler::Get().Record(name, start_ns, end_ns);
}
int pt_prof_dump(const char* path) {
  return ptcore::Profiler::Get().DumpChromeTrace(path) ? 0 : -1;
}
void pt_prof_clear() { ptcore::Profiler::Get().Clear(); }
uint64_t pt_prof_count() { return ptcore::Profiler::Get().Count(); }
}
