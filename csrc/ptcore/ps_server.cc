// Native parameter server.
//
// Capability parity with the reference's PS family
// (operators/distributed/: rpc_server + request handlers, Communicator
// server side; operators/distributed_ops/listen_and_serv_op.h:56 —
// server-side optimize blocks; large_scale_kv.h:762 sparse tables;
// heart_beat_monitor.h:54) — re-designed as a compact TCP RPC server:
// length-prefixed binary frames, thread-per-connection, mutex-guarded
// tables, server-side SGD/momentum/Adam/adagrad rules, counting barriers,
// per-trainer heartbeats. The TPU workers run XLA compute and talk to this
// CPU-host server over DCN (SURVEY.md §2.3 PS row).
//
// Frame: u32 payload_len | payload. Payload: u8 cmd | cmd-specific bytes.
// Strings: u16 len | bytes. Arrays: u64 count | raw little-endian data.
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <set>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ptcore {
namespace ps {

enum Cmd : uint8_t {
  kPushDense = 1,   // name, apply_mode u8 (0=add-delta, 1=optimize), f32[]
  kPullDense = 2,   // name
  kInitDense = 3,   // name, f32[]
  kPushSparse = 4,  // table, dim u32, keys i64[], grads f32[n*dim]
  kPullSparse = 5,  // table, dim u32, keys i64[]
  kBarrier = 6,     // barrier_id u32
  kShutdown = 7,
  kHeartbeat = 8,   // trainer_id u32
  kNumTrainers = 9,
  kPullDenseIfNewer = 10,  // name, client_version u64 -> version-gated
  kSave = 11,  // path -> snapshot ALL tables (dense + sparse + opt state)
  kLoad = 12,  // path -> restore tables from a kSave snapshot
  kPushSparseBf16 = 13,  // table, dim u32, keys i64[], grads bf16[n*dim]
  kPullSparseBf16 = 14,  // table, dim u32, keys i64[] -> rows bf16
};

// bf16 <-> f32: widen is exact (<<16); narrow is round-to-nearest-even,
// bit-identical to ml_dtypes/numpy astype — the server-side conversion
// replaces the trainer's host-plane widen/narrow with the SAME numerics
// while halving the wire bytes.
static inline float Bf16ToF32(uint16_t b) {
  uint32_t u = ((uint32_t)b) << 16;
  float f;
  memcpy(&f, &u, 4);
  return f;
}

static inline uint16_t F32ToBf16(float f) {
  uint32_t u;
  memcpy(&u, &f, 4);
  if ((u & 0x7F800000u) == 0x7F800000u)  // inf/nan: truncate, keep payload
    return (uint16_t)(u >> 16) | (uint16_t)((u & 0xFFFFu) ? 0x40 : 0);
  u += 0x7FFFu + ((u >> 16) & 1u);
  return (uint16_t)(u >> 16);
}

enum Status : uint8_t { kOk = 0, kErr = 1 };

struct Reader {
  const char* p;
  const char* end;
  bool ok = true;

  template <typename T>
  T Get() {
    if (p + sizeof(T) > end) {
      ok = false;
      return T{};
    }
    T v;
    memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }
  std::string Str() {
    uint16_t n = Get<uint16_t>();
    if (!ok || p + n > end) {
      ok = false;
      return {};
    }
    std::string s(p, n);
    p += n;
    return s;
  }
  const char* Raw(size_t n) {
    if (p + n > end) {
      ok = false;
      return nullptr;
    }
    const char* q = p;
    p += n;
    return q;
  }
};

struct Writer {
  std::vector<char> buf;

  template <typename T>
  void Put(T v) {
    size_t o = buf.size();
    buf.resize(o + sizeof(T));
    memcpy(&buf[o], &v, sizeof(T));
  }
  void Str(const std::string& s) {
    Put<uint16_t>((uint16_t)s.size());
    size_t o = buf.size();
    buf.resize(o + s.size());
    memcpy(&buf[o], s.data(), s.size());
  }
  void Raw(const void* d, size_t n) {
    size_t o = buf.size();
    buf.resize(o + n);
    memcpy(&buf[o], d, n);
  }
};

// server-side optimizer rules (listen_and_serv optimize-block capability)
struct DenseTable {
  std::vector<float> value;
  std::vector<float> m, v;  // momentum / adam state
  int64_t step = 0;
  uint64_t version = 0;  // bumps on every mutation (delta-pull gate)
  std::mutex mu;
};

struct SparseTable {
  // key -> [dim floats] + per-key adagrad accumulator
  std::unordered_map<int64_t, std::vector<float>> rows;
  std::unordered_map<int64_t, std::vector<float>> accum;
  uint32_t dim = 0;
  uint64_t seed = 1;
  std::mutex mu;

  std::vector<float>& Row(int64_t key) {
    auto it = rows.find(key);
    if (it != rows.end()) return it->second;
    // lazy init: small deterministic uniform(-0.05, 0.05) per key
    std::vector<float> init(dim);
    uint64_t s = seed ^ (uint64_t)key * 0x9E3779B97F4A7C15ull;
    for (uint32_t k = 0; k < dim; ++k) {
      s = s * 6364136223846793005ull + 1442695040888963407ull;
      init[k] = ((s >> 33) % 10000) / 10000.0f * 0.1f - 0.05f;
    }
    return rows.emplace(key, std::move(init)).first->second;
  }
};

class Server {
 public:
  Server(int expected_trainers, const std::string& opt, double lr)
      : ntrainers_(expected_trainers), opt_(opt), lr_((float)lr) {}

  bool Start(int port) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(fd_, (sockaddr*)&addr, sizeof(addr)) != 0) return false;
    socklen_t len = sizeof(addr);
    getsockname(fd_, (sockaddr*)&addr, &len);
    port_ = ntohs(addr.sin_port);
    if (listen(fd_, 64) != 0) return false;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  int Port() const { return port_; }

  void Stop() {
    if (stopping_.exchange(true)) return;
    {
      // wake any Serve thread parked in a barrier wait (lost-wakeup safe:
      // notify under the same mutex the waiters hold)
      std::lock_guard<std::mutex> lk(barrier_mu_);
      barrier_cv_.notify_all();
    }
    shutdown(fd_, SHUT_RDWR);
    close(fd_);
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int c : conns_) shutdown(c, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // Serve threads are detached; wait for them to drain
    std::unique_lock<std::mutex> lk(conn_mu_);
    done_cv_.wait_for(lk, std::chrono::seconds(5),
                      [&] { return active_serves_ == 0; });
  }

  ~Server() { Stop(); }

  // true once a client sent kShutdown; standalone pserver loops poll this
  bool ShutdownRequested() const { return shutdown_req_ || stopping_; }

  // heartbeat monitor capability: trainers last-seen, in ms-since-start
  int StaleTrainers(int64_t timeout_ms) {
    std::lock_guard<std::mutex> lk(hb_mu_);
    int64_t now = NowMs();
    int stale = 0;
    for (auto& [tid, t] : last_seen_)
      if (now - t > timeout_ms) stale++;
    return stale;
  }

 private:
  static int64_t NowMs() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void AcceptLoop() {
    while (!stopping_) {
      int c = accept(fd_, nullptr, nullptr);
      if (c < 0) break;
      int one = 1;
      setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        conns_.insert(c);
        active_serves_++;
      }
      std::thread([this, c] {
        Serve(c);
        std::lock_guard<std::mutex> lk(conn_mu_);
        conns_.erase(c);
        active_serves_--;
        done_cv_.notify_all();
      }).detach();
    }
  }

  static bool ReadN(int fd, char* buf, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = recv(fd, buf + got, n - got, 0);
      if (r <= 0) return false;
      got += (size_t)r;
    }
    return true;
  }

  static bool WriteN(int fd, const char* buf, size_t n) {
    size_t sent = 0;
    while (sent < n) {
      ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
      if (r <= 0) return false;
      sent += (size_t)r;
    }
    return true;
  }

  void Serve(int c) {
    std::vector<char> payload;
    while (!stopping_) {
      uint32_t len = 0;
      if (!ReadN(c, (char*)&len, 4)) break;
      if (len > (256u << 20)) break;  // 256MB frame cap
      payload.resize(len);
      if (!ReadN(c, payload.data(), len)) break;
      Writer resp;
      try {
        Handle(payload, &resp);
      } catch (const std::exception& e) {  // bad_alloc etc: fail the call,
        resp.buf.clear();                  // not the whole server
        Err(&resp, std::string("server exception: ") + e.what());
      }
      uint32_t rlen = (uint32_t)resp.buf.size();
      if (!WriteN(c, (const char*)&rlen, 4)) break;
      if (!WriteN(c, resp.buf.data(), rlen)) break;
    }
    close(c);
  }

  // wire counts must fit inside the remaining payload (overflow-safe)
  static bool FitsRaw(const Reader& r, uint64_t n, uint64_t elem) {
    uint64_t avail = (uint64_t)(r.end - r.p);
    return elem == 0 || n <= avail / elem;
  }

  void Handle(const std::vector<char>& payload, Writer* resp) {
    Reader r{payload.data(), payload.data() + payload.size()};
    uint8_t cmd = r.Get<uint8_t>();
    switch (cmd) {
      case kInitDense: {
        std::string name = r.Str();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || !FitsRaw(r, n, 4)) return Err(resp, "bad init_dense");
        const char* data = r.Raw(n * 4);
        if (!r.ok) return Err(resp, "bad init_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        t.value.resize(n);
        memcpy(t.value.data(), data, n * 4);
        t.m.assign(n, 0.0f);
        t.v.assign(n, 0.0f);
        t.step = 0;
        ++t.version;
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPushDense: {
        std::string name = r.Str();
        uint8_t mode = r.Get<uint8_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || !FitsRaw(r, n, 4)) return Err(resp, "bad push_dense");
        const char* data = r.Raw(n * 4);
        if (!r.ok) return Err(resp, "bad push_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.value.size() != n)
          return Err(resp, "push_dense: size mismatch for " + name);
        const float* g = (const float*)data;
        if (mode == 0) {  // add delta (GEO-SGD)
          for (uint64_t k = 0; k < n; ++k) t.value[k] += g[k];
        } else {
          ApplyDense(t, g, n);
        }
        ++t.version;
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPullDense: {
        std::string name = r.Str();
        if (!r.ok) return Err(resp, "bad pull_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>((uint64_t)t.value.size());
        resp->Raw(t.value.data(), t.value.size() * 4);
        return;
      }
      case kPullDenseIfNewer: {
        // the async PullDenseWorker's delta gate: data travels only
        // when the server-side table advanced past the client's copy
        std::string name = r.Str();
        uint64_t cver = r.Get<uint64_t>();
        if (!r.ok) return Err(resp, "bad pull_dense_if_newer");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.version == 0 && t.value.empty())
          return Err(resp, "pull_dense_if_newer: " + name +
                           " was never initialized");
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>(t.version);
        if (t.version > cver) {
          resp->Put<uint8_t>(1);
          resp->Put<uint64_t>((uint64_t)t.value.size());
          resp->Raw(t.value.data(), t.value.size() * 4);
        } else {
          resp->Put<uint8_t>(0);
        }
        return;
      }
      case kPushSparse: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad push_sparse");
        const char* keys = r.Raw(n * 8);
        if (!r.ok || !FitsRaw(r, n, (uint64_t)dim * 4))
          return Err(resp, "bad push_sparse");
        const char* grads = r.Raw((uint64_t)n * dim * 4);
        if (!r.ok) return Err(resp, "bad push_sparse");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "push_sparse: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        const int64_t* kk = (const int64_t*)keys;
        const float* gg = (const float*)grads;
        for (uint64_t i = 0; i < n; ++i)
          ApplySparse(t, kk[i], gg + i * dim);
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPullSparse: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad pull_sparse");
        const char* keys = r.Raw(n * 8);
        if (!r.ok) return Err(resp, "bad pull_sparse");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "pull_sparse: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>(n);
        const int64_t* kk = (const int64_t*)keys;
        for (uint64_t i = 0; i < n; ++i)
          resp->Raw(t.Row(kk[i]).data(), dim * 4);
        return;
      }
      case kBarrier: {
        uint32_t bid = r.Get<uint32_t>();
        std::unique_lock<std::mutex> lk(barrier_mu_);
        int gen = barrier_gen_[bid];
        if (++barrier_count_[bid] >= ntrainers_) {
          barrier_count_[bid] = 0;
          barrier_gen_[bid]++;
          barrier_cv_.notify_all();
        } else {
          barrier_cv_.wait(lk, [&] {
            return barrier_gen_[bid] != gen || stopping_ || shutdown_req_;
          });
          if (barrier_gen_[bid] == gen) {
            // released by shutdown, not by the barrier completing: undo our
            // arrival and fail loudly so stragglers don't proceed as synced
            if (barrier_count_[bid] > 0) barrier_count_[bid]--;
            return Err(resp, "server shutting down");
          }
        }
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPushSparseBf16: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad push_sparse_bf16");
        const char* keys = r.Raw(n * 8);
        if (!r.ok || !FitsRaw(r, n, (uint64_t)dim * 2))
          return Err(resp, "bad push_sparse_bf16");
        const char* grads = r.Raw((uint64_t)n * dim * 2);
        if (!r.ok) return Err(resp, "bad push_sparse_bf16");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "push_sparse_bf16: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        const int64_t* kk = (const int64_t*)keys;
        const uint16_t* gg = (const uint16_t*)grads;
        std::vector<float> wide(dim);
        for (uint64_t i = 0; i < n; ++i) {
          for (uint32_t k = 0; k < dim; ++k)
            wide[k] = Bf16ToF32(gg[i * dim + k]);
          ApplySparse(t, kk[i], wide.data());
        }
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPullSparseBf16: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad pull_sparse_bf16");
        const char* keys = r.Raw(n * 8);
        if (!r.ok) return Err(resp, "bad pull_sparse_bf16");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "pull_sparse_bf16: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>(n);
        const int64_t* kk = (const int64_t*)keys;
        std::vector<uint16_t> narrow(dim);
        for (uint64_t i = 0; i < n; ++i) {
          auto& row = t.Row(kk[i]);
          for (uint32_t k = 0; k < dim; ++k)
            narrow[k] = F32ToBf16(row[k]);
          resp->Raw(narrow.data(), (uint64_t)dim * 2);
        }
        return;
      }
      case kSave: {
        // server-side table snapshot (checkpoint_notify_op.cc:66 +
        // recv_save_op.cc + large_scale_kv.h:762 save capability): the
        // trainer notifies, the SERVER owns the IO — dense values with
        // optimizer slots, sparse rows with adagrad accumulators.
        std::string path = r.Str();
        if (!r.ok) return Err(resp, "bad save");
        std::string err;
        if (!Snapshot(path, &err)) return Err(resp, err);
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kLoad: {
        std::string path = r.Str();
        if (!r.ok) return Err(resp, "bad load");
        std::string err;
        if (!Restore(path, &err)) return Err(resp, err);
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kHeartbeat: {
        uint32_t tid = r.Get<uint32_t>();
        std::lock_guard<std::mutex> lk(hb_mu_);
        last_seen_[tid] = NowMs();
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kNumTrainers: {
        resp->Put<uint8_t>(kOk);
        resp->Put<uint32_t>((uint32_t)ntrainers_);
        return;
      }
      case kShutdown: {
        resp->Put<uint8_t>(kOk);
        // only REQUEST shutdown here; stopping_ must stay false so a later
        // Stop() (pt_ps_server_stop / ~Server) still runs its full teardown
        // — joining accept_thread_ — instead of early-returning and leaving
        // a joinable std::thread to std::terminate the process.
        shutdown_req_ = true;
        {
          std::lock_guard<std::mutex> lk(barrier_mu_);
          barrier_cv_.notify_all();
        }
        // wake the listener so AcceptLoop exits
        shutdown(fd_, SHUT_RDWR);
        return;
      }
      default:
        return Err(resp, "unknown cmd");
    }
  }

  void Err(Writer* resp, const std::string& msg) {
    resp->Put<uint8_t>(kErr);
    resp->Str(msg);
  }

  // ---- snapshot/restore (binary, atomic-rename; format PTPS1) ----
  // u32 magic 'PTPS' | u8 version | u32 ndense | per dense:
  //   str name | i64 step | u64 version | u64 n | f32 value[n] m[n] v[n]
  // u32 nsparse | per sparse: str name | u32 dim | u64 seed | u64 nrows |
  //   per row: i64 key | f32 row[dim] | u8 has_accum | f32 accum[dim]?
  static void WStr(FILE* f, const std::string& s) {
    uint16_t n = (uint16_t)s.size();
    fwrite(&n, 2, 1, f);
    fwrite(s.data(), 1, n, f);
  }
  static bool RStr(FILE* f, std::string* s) {
    uint16_t n = 0;
    if (fread(&n, 2, 1, f) != 1) return false;
    s->resize(n);
    return n == 0 || fread(&(*s)[0], 1, n, f) == n;
  }

  bool Snapshot(const std::string& path, std::string* err) {
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) {
      *err = "save: cannot open " + tmp;
      return false;
    }
    uint32_t magic = 0x53505450;  // 'PTPS'
    uint8_t ver = 1;
    fwrite(&magic, 4, 1, f);
    fwrite(&ver, 1, 1, f);
    // copy the name lists under tables_mu_, then lock tables one by one
    std::vector<std::string> dnames, snames;
    {
      std::lock_guard<std::mutex> lk(tables_mu_);
      for (auto& [n, t] : dense_) dnames.push_back(n);
      for (auto& [n, t] : sparse_) snames.push_back(n);
    }
    uint32_t nd = (uint32_t)dnames.size();
    fwrite(&nd, 4, 1, f);
    for (auto& name : dnames) {
      auto& t = Dense(name);
      std::lock_guard<std::mutex> lk(t.mu);
      WStr(f, name);
      fwrite(&t.step, 8, 1, f);
      fwrite(&t.version, 8, 1, f);
      uint64_t n = t.value.size();
      fwrite(&n, 8, 1, f);
      fwrite(t.value.data(), 4, n, f);
      // m/v may be unsized for never-optimized tables; pad to n
      std::vector<float> m(t.m), v(t.v);
      m.resize(n, 0.0f);
      v.resize(n, 0.0f);
      fwrite(m.data(), 4, n, f);
      fwrite(v.data(), 4, n, f);
    }
    uint32_t ns = (uint32_t)snames.size();
    fwrite(&ns, 4, 1, f);
    for (auto& name : snames) {
      auto& t = Sparse(name, 0);
      std::lock_guard<std::mutex> lk(t.mu);
      WStr(f, name);
      fwrite(&t.dim, 4, 1, f);
      fwrite(&t.seed, 8, 1, f);
      uint64_t nrows = t.rows.size();
      fwrite(&nrows, 8, 1, f);
      for (auto& [key, row] : t.rows) {
        fwrite(&key, 8, 1, f);
        fwrite(row.data(), 4, t.dim, f);
        auto it = t.accum.find(key);
        uint8_t has = it != t.accum.end() ? 1 : 0;
        fwrite(&has, 1, 1, f);
        if (has) fwrite(it->second.data(), 4, t.dim, f);
      }
    }
    bool okio = ferror(f) == 0;
    okio = (fclose(f) == 0) && okio;
    if (!okio || rename(tmp.c_str(), path.c_str()) != 0) {
      *err = "save: write/rename failed for " + path;
      remove(tmp.c_str());
      return false;
    }
    return true;
  }

  bool Restore(const std::string& path, std::string* err) {
    // Parse the WHOLE snapshot into staging structures first (using the
    // bounds-checked Reader over the in-memory file, so corrupt counts
    // fail the parse instead of allocating), then swap into the live
    // tables — a bad file must never leave the server half-restored.
    FILE* f = fopen(path.c_str(), "rb");
    if (!f) {
      *err = "load: cannot open " + path;
      return false;
    }
    fseek(f, 0, SEEK_END);
    long fsize = ftell(f);
    fseek(f, 0, SEEK_SET);
    std::vector<char> buf((size_t)(fsize > 0 ? fsize : 0));
    bool rd = fsize > 0 &&
              fread(buf.data(), 1, buf.size(), f) == buf.size();
    fclose(f);
    if (!rd) {
      *err = "load: cannot read " + path;
      return false;
    }
    Reader r{buf.data(), buf.data() + buf.size()};
    struct DStage {
      std::string name;
      int64_t step;
      uint64_t version;
      std::vector<float> value, m, v;
    };
    struct SStage {
      std::string name;
      uint32_t dim;
      uint64_t seed;
      std::unordered_map<int64_t, std::vector<float>> rows, accum;
    };
    std::vector<DStage> dstage;
    std::vector<SStage> sstage;
    uint32_t magic = r.Get<uint32_t>();
    uint8_t ver = r.Get<uint8_t>();
    if (!r.ok || magic != 0x53505450 || ver != 1) {
      *err = "load: not a PTPS1 snapshot: " + path;
      return false;
    }
    uint32_t nd = r.Get<uint32_t>();
    for (uint32_t i = 0; r.ok && i < nd; ++i) {
      DStage d;
      d.name = r.Str();
      d.step = r.Get<int64_t>();
      d.version = r.Get<uint64_t>();
      uint64_t n = r.Get<uint64_t>();
      if (!r.ok || !FitsRaw(r, n, 12)) {
        r.ok = false;
        break;
      }
      const char* pv = r.Raw(n * 4);
      const char* pm = r.Raw(n * 4);
      const char* pvv = r.Raw(n * 4);
      if (!r.ok) break;
      d.value.assign((const float*)pv, (const float*)pv + n);
      d.m.assign((const float*)pm, (const float*)pm + n);
      d.v.assign((const float*)pvv, (const float*)pvv + n);
      dstage.push_back(std::move(d));
    }
    uint32_t nsp = r.ok ? r.Get<uint32_t>() : 0;
    for (uint32_t i = 0; r.ok && i < nsp; ++i) {
      SStage s;
      s.name = r.Str();
      s.dim = r.Get<uint32_t>();
      s.seed = r.Get<uint64_t>();
      uint64_t nrows = r.Get<uint64_t>();
      if (!r.ok || s.dim == 0 ||
          !FitsRaw(r, nrows, 9 + (uint64_t)s.dim * 4)) {
        r.ok = false;
        break;
      }
      s.rows.reserve(nrows);
      for (uint64_t rix = 0; r.ok && rix < nrows; ++rix) {
        int64_t key = r.Get<int64_t>();
        const char* prow = r.Raw((uint64_t)s.dim * 4);
        uint8_t has = r.Get<uint8_t>();
        if (!r.ok) break;
        s.rows.emplace(key, std::vector<float>(
                                (const float*)prow,
                                (const float*)prow + s.dim));
        if (has) {
          const char* pacc = r.Raw((uint64_t)s.dim * 4);
          if (!r.ok) break;
          s.accum.emplace(key, std::vector<float>(
                                   (const float*)pacc,
                                   (const float*)pacc + s.dim));
        }
      }
      if (r.ok) sstage.push_back(std::move(s));
    }
    if (!r.ok) {
      *err = "load: corrupt or truncated snapshot " + path;
      return false;
    }
    // whole file validated — swap into the live tables
    for (auto& d : dstage) {
      auto& t = Dense(d.name);
      std::lock_guard<std::mutex> lk(t.mu);
      t.value = std::move(d.value);
      t.m = std::move(d.m);
      t.v = std::move(d.v);
      t.step = d.step;
      // never move the version backwards: a delta-pull client holding a
      // higher version would otherwise never refresh after a rollback
      t.version = std::max(t.version, d.version) + 1;
    }
    for (auto& s : sstage) {
      auto& t = Sparse(s.name, s.dim);
      std::lock_guard<std::mutex> lk(t.mu);
      t.dim = s.dim;
      t.seed = s.seed;
      t.rows = std::move(s.rows);
      t.accum = std::move(s.accum);
    }
    return true;
  }

  DenseTable& Dense(const std::string& name) {
    std::lock_guard<std::mutex> lk(tables_mu_);
    return dense_[name];
  }

  SparseTable& Sparse(const std::string& name, uint32_t dim) {
    std::lock_guard<std::mutex> lk(tables_mu_);
    auto& t = sparse_[name];
    if (t.dim == 0) t.dim = dim;
    return t;
  }

  void ApplyDense(DenseTable& t, const float* g, uint64_t n) {
    t.step++;
    if (opt_ == "sgd") {
      for (uint64_t k = 0; k < n; ++k) t.value[k] -= lr_ * g[k];
    } else if (opt_ == "momentum") {
      const float mu = 0.9f;
      for (uint64_t k = 0; k < n; ++k) {
        t.m[k] = mu * t.m[k] + g[k];
        t.value[k] -= lr_ * t.m[k];
      }
    } else {  // adam
      const float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
      float bc1 = 1.0f - powf(b1, (float)t.step);
      float bc2 = 1.0f - powf(b2, (float)t.step);
      for (uint64_t k = 0; k < n; ++k) {
        t.m[k] = b1 * t.m[k] + (1 - b1) * g[k];
        t.v[k] = b2 * t.v[k] + (1 - b2) * g[k] * g[k];
        t.value[k] -=
            lr_ * (t.m[k] / bc1) / (sqrtf(t.v[k] / bc2) + eps);
      }
    }
  }

  void ApplySparse(SparseTable& t, int64_t key, const float* g) {
    auto& row = t.Row(key);
    auto& acc = t.accum[key];
    if (acc.empty()) acc.assign(t.dim, 0.0f);
    // adagrad (large-scale sparse default; stable for embeddings)
    for (uint32_t k = 0; k < t.dim; ++k) {
      acc[k] += g[k] * g[k];
      row[k] -= lr_ * g[k] / (sqrtf(acc[k]) + 1e-8f);
    }
  }

  int fd_ = -1;
  int port_ = 0;
  int ntrainers_;
  std::string opt_;
  float lr_;
  std::atomic<bool> stopping_{false};
  std::atomic<bool> shutdown_req_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::condition_variable done_cv_;
  std::set<int> conns_;
  int active_serves_ = 0;

  std::mutex tables_mu_;
  std::map<std::string, DenseTable> dense_;
  std::map<std::string, SparseTable> sparse_;

  std::mutex barrier_mu_;
  std::condition_variable barrier_cv_;
  std::map<uint32_t, int> barrier_count_, barrier_gen_;

  std::mutex hb_mu_;
  std::map<uint32_t, int64_t> last_seen_;
};

// ------------------------- client -------------------------

class Client {
 public:
  bool Connect(const std::string& host, int port) {
    // resolve hostnames too (real PS deployments address servers by name)
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    int rc = getaddrinfo(host.c_str(), std::to_string(port).c_str(),
                         &hints, &res);
    if (rc != 0 || !res) {
      error = "cannot resolve host '" + host + "': " + gai_strerror(rc);
      return false;
    }
    fd_ = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      error = "connect to " + host + ":" + std::to_string(port) +
              " failed";
      freeaddrinfo(res);
      return false;
    }
    freeaddrinfo(res);
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return true;
  }

  ~Client() {
    if (fd_ >= 0) close(fd_);
  }

  bool Call(const Writer& req, std::vector<char>* resp) {
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t len = (uint32_t)req.buf.size();
    if (!WriteAll((const char*)&len, 4) ||
        !WriteAll(req.buf.data(), len)) {
      error = "send failed";
      return false;
    }
    uint32_t rlen = 0;
    if (!ReadAll((char*)&rlen, 4)) {
      error = "recv failed";
      return false;
    }
    resp->resize(rlen);
    if (!ReadAll(resp->data(), rlen)) {
      error = "recv failed";
      return false;
    }
    return true;
  }

  std::string error;

 private:
  bool WriteAll(const char* b, size_t n) {
    size_t s = 0;
    while (s < n) {
      ssize_t r = send(fd_, b + s, n - s, MSG_NOSIGNAL);
      if (r <= 0) return false;
      s += (size_t)r;
    }
    return true;
  }
  bool ReadAll(char* b, size_t n) {
    size_t s = 0;
    while (s < n) {
      ssize_t r = recv(fd_, b + s, n - s, 0);
      if (r <= 0) return false;
      s += (size_t)r;
    }
    return true;
  }

  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace ps
}  // namespace ptcore

// ------------------------- C API -------------------------

using ptcore::ps::Client;
using ptcore::ps::Server;
using ptcore::ps::Writer;

extern "C" {

void* pt_ps_server_start(int port, int expected_trainers, const char* opt,
                         double lr) {
  auto* s = new Server(expected_trainers, opt, lr);
  if (!s->Start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}
int pt_ps_server_port(void* h) { return ((Server*)h)->Port(); }
void pt_ps_server_stop(void* h) { ((Server*)h)->Stop(); }
void pt_ps_server_destroy(void* h) { delete (Server*)h; }
int pt_ps_server_stale(void* h, int64_t timeout_ms) {
  return ((Server*)h)->StaleTrainers(timeout_ms);
}
int pt_ps_server_shutdown_requested(void* h) {
  return ((Server*)h)->ShutdownRequested() ? 1 : 0;
}

void* pt_ps_connect(const char* host, int port) {
  auto* c = new Client;
  if (!c->Connect(host, port)) {
    delete c;
    return nullptr;
  }
  return c;
}
void pt_ps_disconnect(void* h) { delete (Client*)h; }
const char* pt_ps_client_error(void* h) {
  return ((Client*)h)->error.c_str();
}

static thread_local std::vector<char> g_resp;

// surface the server's Err string (payload after kErr status) to callers
static void CaptureServerError(Client* c) {
  if (g_resp.size() >= 3) {
    uint16_t nl = 0;
    memcpy(&nl, g_resp.data() + 1, 2);
    if (3 + (size_t)nl <= g_resp.size()) {
      c->error.assign(g_resp.data() + 3, nl);
      return;
    }
  }
  c->error = "server returned error (no detail)";
}

static int SimpleCall(Client* c, Writer& w) {
  if (!c->Call(w, &g_resp)) return -1;
  if (!g_resp.empty() && g_resp[0] == 0) return 0;
  CaptureServerError(c);
  return -2;
}

int pt_ps_init_dense(void* h, const char* name, const float* data,
                     uint64_t n) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kInitDense);
  w.Str(name);
  w.Put<uint64_t>(n);
  w.Raw(data, n * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_push_dense(void* h, const char* name, const float* grad,
                     uint64_t n, int optimize) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPushDense);
  w.Str(name);
  w.Put<uint8_t>((uint8_t)(optimize ? 1 : 0));
  w.Put<uint64_t>(n);
  w.Raw(grad, n * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_pull_dense(void* h, const char* name, float* out, uint64_t n) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullDense);
  w.Str(name);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 9) {
    c->error = "pull_dense: truncated response header";
    return -4;
  }
  uint64_t count = 0;
  memcpy(&count, g_resp.data() + 1, 8);
  if (count != n) {
    c->error = "pull_dense size mismatch: server has " +
               std::to_string(count) + ", caller expects " +
               std::to_string(n);
    return -3;
  }
  if (g_resp.size() < 9 + (uint64_t)n * 4) {
    c->error = "pull_dense: truncated response payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 9, n * 4);
  return 0;
}

int pt_ps_pull_dense_if_newer(void* h, const char* name, float* out,
                              uint64_t n, uint64_t* version_io) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullDenseIfNewer);
  w.Str(name);
  w.Put<uint64_t>(*version_io);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 10) {
    c->error = "pull_dense_if_newer: truncated header";
    return -4;
  }
  uint64_t ver = 0;
  memcpy(&ver, g_resp.data() + 1, 8);
  uint8_t has = (uint8_t)g_resp[9];
  *version_io = ver;
  if (!has) return 1;  // unchanged: no payload transferred
  if (g_resp.size() < 18) {
    c->error = "pull_dense_if_newer: truncated count";
    return -4;
  }
  uint64_t count = 0;
  memcpy(&count, g_resp.data() + 10, 8);
  if (count != n) {
    c->error = "pull_dense_if_newer size mismatch";
    return -3;
  }
  if (g_resp.size() < 18 + (uint64_t)n * 4) {
    c->error = "pull_dense_if_newer: truncated payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 18, n * 4);
  return 0;
}

int pt_ps_push_sparse(void* h, const char* table, uint32_t dim,
                      const int64_t* keys, uint64_t n, const float* grads) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPushSparse);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  w.Raw(grads, (uint64_t)n * dim * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_pull_sparse(void* h, const char* table, uint32_t dim,
                      const int64_t* keys, uint64_t n, float* out) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullSparse);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 9 + (uint64_t)n * dim * 4) {
    c->error = "pull_sparse: truncated response payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 9, (uint64_t)n * dim * 4);
  return 0;
}

int pt_ps_push_sparse_bf16(void* h, const char* table, uint32_t dim,
                           const int64_t* keys, uint64_t n,
                           const uint16_t* grads) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPushSparseBf16);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  w.Raw(grads, (uint64_t)n * dim * 2);
  return SimpleCall((Client*)h, w);
}

int pt_ps_pull_sparse_bf16(void* h, const char* table, uint32_t dim,
                           const int64_t* keys, uint64_t n, uint16_t* out) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullSparseBf16);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 9 + (uint64_t)n * dim * 2) {
    c->error = "pull_sparse_bf16: truncated response payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 9, (uint64_t)n * dim * 2);
  return 0;
}

int pt_ps_barrier(void* h, uint32_t barrier_id) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kBarrier);
  w.Put<uint32_t>(barrier_id);
  return SimpleCall((Client*)h, w);
}

int pt_ps_heartbeat(void* h, uint32_t trainer_id) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kHeartbeat);
  w.Put<uint32_t>(trainer_id);
  return SimpleCall((Client*)h, w);
}

int pt_ps_shutdown(void* h) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kShutdown);
  return SimpleCall((Client*)h, w);
}

int pt_ps_save(void* h, const char* path) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kSave);
  w.Str(path);
  return SimpleCall((Client*)h, w);
}

int pt_ps_load(void* h, const char* path) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kLoad);
  w.Str(path);
  return SimpleCall((Client*)h, w);
}

}  // extern "C"
