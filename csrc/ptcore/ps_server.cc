// Native parameter server.
//
// Capability parity with the reference's PS family
// (operators/distributed/: rpc_server + request handlers, Communicator
// server side; operators/distributed_ops/listen_and_serv_op.h:56 —
// server-side optimize blocks; large_scale_kv.h:762 sparse tables;
// heart_beat_monitor.h:54) — re-designed as a compact TCP RPC server:
// length-prefixed binary frames, thread-per-connection, mutex-guarded
// tables, server-side SGD/momentum/Adam/adagrad rules, counting barriers,
// per-trainer heartbeats. The TPU workers run XLA compute and talk to this
// CPU-host server over DCN (SURVEY.md §2.3 PS row).
//
// Frame: u32 payload_len | payload. Payload: u8 cmd | cmd-specific bytes.
// Strings: u16 len | bytes. Arrays: u64 count | raw little-endian data.
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <set>

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ptcore {
namespace ps {

enum Cmd : uint8_t {
  kPushDense = 1,   // name, apply_mode u8 (0=add-delta, 1=optimize), f32[]
  kPullDense = 2,   // name
  kInitDense = 3,   // name, f32[]
  kPushSparse = 4,  // table, dim u32, keys i64[], grads f32[n*dim]
  kPullSparse = 5,  // table, dim u32, keys i64[]
  kBarrier = 6,     // barrier_id u32
  kShutdown = 7,
  kHeartbeat = 8,   // trainer_id u32
  kNumTrainers = 9,
  kPullDenseIfNewer = 10,  // name, client_version u64 -> version-gated
};

enum Status : uint8_t { kOk = 0, kErr = 1 };

struct Reader {
  const char* p;
  const char* end;
  bool ok = true;

  template <typename T>
  T Get() {
    if (p + sizeof(T) > end) {
      ok = false;
      return T{};
    }
    T v;
    memcpy(&v, p, sizeof(T));
    p += sizeof(T);
    return v;
  }
  std::string Str() {
    uint16_t n = Get<uint16_t>();
    if (!ok || p + n > end) {
      ok = false;
      return {};
    }
    std::string s(p, n);
    p += n;
    return s;
  }
  const char* Raw(size_t n) {
    if (p + n > end) {
      ok = false;
      return nullptr;
    }
    const char* q = p;
    p += n;
    return q;
  }
};

struct Writer {
  std::vector<char> buf;

  template <typename T>
  void Put(T v) {
    size_t o = buf.size();
    buf.resize(o + sizeof(T));
    memcpy(&buf[o], &v, sizeof(T));
  }
  void Str(const std::string& s) {
    Put<uint16_t>((uint16_t)s.size());
    size_t o = buf.size();
    buf.resize(o + s.size());
    memcpy(&buf[o], s.data(), s.size());
  }
  void Raw(const void* d, size_t n) {
    size_t o = buf.size();
    buf.resize(o + n);
    memcpy(&buf[o], d, n);
  }
};

// server-side optimizer rules (listen_and_serv optimize-block capability)
struct DenseTable {
  std::vector<float> value;
  std::vector<float> m, v;  // momentum / adam state
  int64_t step = 0;
  uint64_t version = 0;  // bumps on every mutation (delta-pull gate)
  std::mutex mu;
};

struct SparseTable {
  // key -> [dim floats] + per-key adagrad accumulator
  std::unordered_map<int64_t, std::vector<float>> rows;
  std::unordered_map<int64_t, std::vector<float>> accum;
  uint32_t dim = 0;
  uint64_t seed = 1;
  std::mutex mu;

  std::vector<float>& Row(int64_t key) {
    auto it = rows.find(key);
    if (it != rows.end()) return it->second;
    // lazy init: small deterministic uniform(-0.05, 0.05) per key
    std::vector<float> init(dim);
    uint64_t s = seed ^ (uint64_t)key * 0x9E3779B97F4A7C15ull;
    for (uint32_t k = 0; k < dim; ++k) {
      s = s * 6364136223846793005ull + 1442695040888963407ull;
      init[k] = ((s >> 33) % 10000) / 10000.0f * 0.1f - 0.05f;
    }
    return rows.emplace(key, std::move(init)).first->second;
  }
};

class Server {
 public:
  Server(int expected_trainers, const std::string& opt, double lr)
      : ntrainers_(expected_trainers), opt_(opt), lr_((float)lr) {}

  bool Start(int port) {
    fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons((uint16_t)port);
    if (bind(fd_, (sockaddr*)&addr, sizeof(addr)) != 0) return false;
    socklen_t len = sizeof(addr);
    getsockname(fd_, (sockaddr*)&addr, &len);
    port_ = ntohs(addr.sin_port);
    if (listen(fd_, 64) != 0) return false;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  int Port() const { return port_; }

  void Stop() {
    if (stopping_.exchange(true)) return;
    {
      // wake any Serve thread parked in a barrier wait (lost-wakeup safe:
      // notify under the same mutex the waiters hold)
      std::lock_guard<std::mutex> lk(barrier_mu_);
      barrier_cv_.notify_all();
    }
    shutdown(fd_, SHUT_RDWR);
    close(fd_);
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int c : conns_) shutdown(c, SHUT_RDWR);
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    // Serve threads are detached; wait for them to drain
    std::unique_lock<std::mutex> lk(conn_mu_);
    done_cv_.wait_for(lk, std::chrono::seconds(5),
                      [&] { return active_serves_ == 0; });
  }

  ~Server() { Stop(); }

  // true once a client sent kShutdown; standalone pserver loops poll this
  bool ShutdownRequested() const { return shutdown_req_ || stopping_; }

  // heartbeat monitor capability: trainers last-seen, in ms-since-start
  int StaleTrainers(int64_t timeout_ms) {
    std::lock_guard<std::mutex> lk(hb_mu_);
    int64_t now = NowMs();
    int stale = 0;
    for (auto& [tid, t] : last_seen_)
      if (now - t > timeout_ms) stale++;
    return stale;
  }

 private:
  static int64_t NowMs() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void AcceptLoop() {
    while (!stopping_) {
      int c = accept(fd_, nullptr, nullptr);
      if (c < 0) break;
      int one = 1;
      setsockopt(c, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        conns_.insert(c);
        active_serves_++;
      }
      std::thread([this, c] {
        Serve(c);
        std::lock_guard<std::mutex> lk(conn_mu_);
        conns_.erase(c);
        active_serves_--;
        done_cv_.notify_all();
      }).detach();
    }
  }

  static bool ReadN(int fd, char* buf, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t r = recv(fd, buf + got, n - got, 0);
      if (r <= 0) return false;
      got += (size_t)r;
    }
    return true;
  }

  static bool WriteN(int fd, const char* buf, size_t n) {
    size_t sent = 0;
    while (sent < n) {
      ssize_t r = send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
      if (r <= 0) return false;
      sent += (size_t)r;
    }
    return true;
  }

  void Serve(int c) {
    std::vector<char> payload;
    while (!stopping_) {
      uint32_t len = 0;
      if (!ReadN(c, (char*)&len, 4)) break;
      if (len > (256u << 20)) break;  // 256MB frame cap
      payload.resize(len);
      if (!ReadN(c, payload.data(), len)) break;
      Writer resp;
      try {
        Handle(payload, &resp);
      } catch (const std::exception& e) {  // bad_alloc etc: fail the call,
        resp.buf.clear();                  // not the whole server
        Err(&resp, std::string("server exception: ") + e.what());
      }
      uint32_t rlen = (uint32_t)resp.buf.size();
      if (!WriteN(c, (const char*)&rlen, 4)) break;
      if (!WriteN(c, resp.buf.data(), rlen)) break;
    }
    close(c);
  }

  // wire counts must fit inside the remaining payload (overflow-safe)
  static bool FitsRaw(const Reader& r, uint64_t n, uint64_t elem) {
    uint64_t avail = (uint64_t)(r.end - r.p);
    return elem == 0 || n <= avail / elem;
  }

  void Handle(const std::vector<char>& payload, Writer* resp) {
    Reader r{payload.data(), payload.data() + payload.size()};
    uint8_t cmd = r.Get<uint8_t>();
    switch (cmd) {
      case kInitDense: {
        std::string name = r.Str();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || !FitsRaw(r, n, 4)) return Err(resp, "bad init_dense");
        const char* data = r.Raw(n * 4);
        if (!r.ok) return Err(resp, "bad init_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        t.value.resize(n);
        memcpy(t.value.data(), data, n * 4);
        t.m.assign(n, 0.0f);
        t.v.assign(n, 0.0f);
        t.step = 0;
        ++t.version;
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPushDense: {
        std::string name = r.Str();
        uint8_t mode = r.Get<uint8_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || !FitsRaw(r, n, 4)) return Err(resp, "bad push_dense");
        const char* data = r.Raw(n * 4);
        if (!r.ok) return Err(resp, "bad push_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.value.size() != n)
          return Err(resp, "push_dense: size mismatch for " + name);
        const float* g = (const float*)data;
        if (mode == 0) {  // add delta (GEO-SGD)
          for (uint64_t k = 0; k < n; ++k) t.value[k] += g[k];
        } else {
          ApplyDense(t, g, n);
        }
        ++t.version;
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPullDense: {
        std::string name = r.Str();
        if (!r.ok) return Err(resp, "bad pull_dense");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>((uint64_t)t.value.size());
        resp->Raw(t.value.data(), t.value.size() * 4);
        return;
      }
      case kPullDenseIfNewer: {
        // the async PullDenseWorker's delta gate: data travels only
        // when the server-side table advanced past the client's copy
        std::string name = r.Str();
        uint64_t cver = r.Get<uint64_t>();
        if (!r.ok) return Err(resp, "bad pull_dense_if_newer");
        auto& t = Dense(name);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.version == 0 && t.value.empty())
          return Err(resp, "pull_dense_if_newer: " + name +
                           " was never initialized");
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>(t.version);
        if (t.version > cver) {
          resp->Put<uint8_t>(1);
          resp->Put<uint64_t>((uint64_t)t.value.size());
          resp->Raw(t.value.data(), t.value.size() * 4);
        } else {
          resp->Put<uint8_t>(0);
        }
        return;
      }
      case kPushSparse: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad push_sparse");
        const char* keys = r.Raw(n * 8);
        if (!r.ok || !FitsRaw(r, n, (uint64_t)dim * 4))
          return Err(resp, "bad push_sparse");
        const char* grads = r.Raw((uint64_t)n * dim * 4);
        if (!r.ok) return Err(resp, "bad push_sparse");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "push_sparse: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        const int64_t* kk = (const int64_t*)keys;
        const float* gg = (const float*)grads;
        for (uint64_t i = 0; i < n; ++i)
          ApplySparse(t, kk[i], gg + i * dim);
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kPullSparse: {
        std::string name = r.Str();
        uint32_t dim = r.Get<uint32_t>();
        uint64_t n = r.Get<uint64_t>();
        if (!r.ok || dim == 0 || !FitsRaw(r, n, 8))
          return Err(resp, "bad pull_sparse");
        const char* keys = r.Raw(n * 8);
        if (!r.ok) return Err(resp, "bad pull_sparse");
        auto& t = Sparse(name, dim);
        std::lock_guard<std::mutex> lk(t.mu);
        if (t.dim != dim)
          return Err(resp, "pull_sparse: dim mismatch for " + name +
                               " (table=" + std::to_string(t.dim) +
                               " req=" + std::to_string(dim) + ")");
        resp->Put<uint8_t>(kOk);
        resp->Put<uint64_t>(n);
        const int64_t* kk = (const int64_t*)keys;
        for (uint64_t i = 0; i < n; ++i)
          resp->Raw(t.Row(kk[i]).data(), dim * 4);
        return;
      }
      case kBarrier: {
        uint32_t bid = r.Get<uint32_t>();
        std::unique_lock<std::mutex> lk(barrier_mu_);
        int gen = barrier_gen_[bid];
        if (++barrier_count_[bid] >= ntrainers_) {
          barrier_count_[bid] = 0;
          barrier_gen_[bid]++;
          barrier_cv_.notify_all();
        } else {
          barrier_cv_.wait(lk, [&] {
            return barrier_gen_[bid] != gen || stopping_ || shutdown_req_;
          });
          if (barrier_gen_[bid] == gen) {
            // released by shutdown, not by the barrier completing: undo our
            // arrival and fail loudly so stragglers don't proceed as synced
            if (barrier_count_[bid] > 0) barrier_count_[bid]--;
            return Err(resp, "server shutting down");
          }
        }
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kHeartbeat: {
        uint32_t tid = r.Get<uint32_t>();
        std::lock_guard<std::mutex> lk(hb_mu_);
        last_seen_[tid] = NowMs();
        resp->Put<uint8_t>(kOk);
        return;
      }
      case kNumTrainers: {
        resp->Put<uint8_t>(kOk);
        resp->Put<uint32_t>((uint32_t)ntrainers_);
        return;
      }
      case kShutdown: {
        resp->Put<uint8_t>(kOk);
        // only REQUEST shutdown here; stopping_ must stay false so a later
        // Stop() (pt_ps_server_stop / ~Server) still runs its full teardown
        // — joining accept_thread_ — instead of early-returning and leaving
        // a joinable std::thread to std::terminate the process.
        shutdown_req_ = true;
        {
          std::lock_guard<std::mutex> lk(barrier_mu_);
          barrier_cv_.notify_all();
        }
        // wake the listener so AcceptLoop exits
        shutdown(fd_, SHUT_RDWR);
        return;
      }
      default:
        return Err(resp, "unknown cmd");
    }
  }

  void Err(Writer* resp, const std::string& msg) {
    resp->Put<uint8_t>(kErr);
    resp->Str(msg);
  }

  DenseTable& Dense(const std::string& name) {
    std::lock_guard<std::mutex> lk(tables_mu_);
    return dense_[name];
  }

  SparseTable& Sparse(const std::string& name, uint32_t dim) {
    std::lock_guard<std::mutex> lk(tables_mu_);
    auto& t = sparse_[name];
    if (t.dim == 0) t.dim = dim;
    return t;
  }

  void ApplyDense(DenseTable& t, const float* g, uint64_t n) {
    t.step++;
    if (opt_ == "sgd") {
      for (uint64_t k = 0; k < n; ++k) t.value[k] -= lr_ * g[k];
    } else if (opt_ == "momentum") {
      const float mu = 0.9f;
      for (uint64_t k = 0; k < n; ++k) {
        t.m[k] = mu * t.m[k] + g[k];
        t.value[k] -= lr_ * t.m[k];
      }
    } else {  // adam
      const float b1 = 0.9f, b2 = 0.999f, eps = 1e-8f;
      float bc1 = 1.0f - powf(b1, (float)t.step);
      float bc2 = 1.0f - powf(b2, (float)t.step);
      for (uint64_t k = 0; k < n; ++k) {
        t.m[k] = b1 * t.m[k] + (1 - b1) * g[k];
        t.v[k] = b2 * t.v[k] + (1 - b2) * g[k] * g[k];
        t.value[k] -=
            lr_ * (t.m[k] / bc1) / (sqrtf(t.v[k] / bc2) + eps);
      }
    }
  }

  void ApplySparse(SparseTable& t, int64_t key, const float* g) {
    auto& row = t.Row(key);
    auto& acc = t.accum[key];
    if (acc.empty()) acc.assign(t.dim, 0.0f);
    // adagrad (large-scale sparse default; stable for embeddings)
    for (uint32_t k = 0; k < t.dim; ++k) {
      acc[k] += g[k] * g[k];
      row[k] -= lr_ * g[k] / (sqrtf(acc[k]) + 1e-8f);
    }
  }

  int fd_ = -1;
  int port_ = 0;
  int ntrainers_;
  std::string opt_;
  float lr_;
  std::atomic<bool> stopping_{false};
  std::atomic<bool> shutdown_req_{false};
  std::thread accept_thread_;
  std::mutex conn_mu_;
  std::condition_variable done_cv_;
  std::set<int> conns_;
  int active_serves_ = 0;

  std::mutex tables_mu_;
  std::map<std::string, DenseTable> dense_;
  std::map<std::string, SparseTable> sparse_;

  std::mutex barrier_mu_;
  std::condition_variable barrier_cv_;
  std::map<uint32_t, int> barrier_count_, barrier_gen_;

  std::mutex hb_mu_;
  std::map<uint32_t, int64_t> last_seen_;
};

// ------------------------- client -------------------------

class Client {
 public:
  bool Connect(const std::string& host, int port) {
    // resolve hostnames too (real PS deployments address servers by name)
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    int rc = getaddrinfo(host.c_str(), std::to_string(port).c_str(),
                         &hints, &res);
    if (rc != 0 || !res) {
      error = "cannot resolve host '" + host + "': " + gai_strerror(rc);
      return false;
    }
    fd_ = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (connect(fd_, res->ai_addr, res->ai_addrlen) != 0) {
      error = "connect to " + host + ":" + std::to_string(port) +
              " failed";
      freeaddrinfo(res);
      return false;
    }
    freeaddrinfo(res);
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    return true;
  }

  ~Client() {
    if (fd_ >= 0) close(fd_);
  }

  bool Call(const Writer& req, std::vector<char>* resp) {
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t len = (uint32_t)req.buf.size();
    if (!WriteAll((const char*)&len, 4) ||
        !WriteAll(req.buf.data(), len)) {
      error = "send failed";
      return false;
    }
    uint32_t rlen = 0;
    if (!ReadAll((char*)&rlen, 4)) {
      error = "recv failed";
      return false;
    }
    resp->resize(rlen);
    if (!ReadAll(resp->data(), rlen)) {
      error = "recv failed";
      return false;
    }
    return true;
  }

  std::string error;

 private:
  bool WriteAll(const char* b, size_t n) {
    size_t s = 0;
    while (s < n) {
      ssize_t r = send(fd_, b + s, n - s, MSG_NOSIGNAL);
      if (r <= 0) return false;
      s += (size_t)r;
    }
    return true;
  }
  bool ReadAll(char* b, size_t n) {
    size_t s = 0;
    while (s < n) {
      ssize_t r = recv(fd_, b + s, n - s, 0);
      if (r <= 0) return false;
      s += (size_t)r;
    }
    return true;
  }

  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace ps
}  // namespace ptcore

// ------------------------- C API -------------------------

using ptcore::ps::Client;
using ptcore::ps::Server;
using ptcore::ps::Writer;

extern "C" {

void* pt_ps_server_start(int port, int expected_trainers, const char* opt,
                         double lr) {
  auto* s = new Server(expected_trainers, opt, lr);
  if (!s->Start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}
int pt_ps_server_port(void* h) { return ((Server*)h)->Port(); }
void pt_ps_server_stop(void* h) { ((Server*)h)->Stop(); }
void pt_ps_server_destroy(void* h) { delete (Server*)h; }
int pt_ps_server_stale(void* h, int64_t timeout_ms) {
  return ((Server*)h)->StaleTrainers(timeout_ms);
}
int pt_ps_server_shutdown_requested(void* h) {
  return ((Server*)h)->ShutdownRequested() ? 1 : 0;
}

void* pt_ps_connect(const char* host, int port) {
  auto* c = new Client;
  if (!c->Connect(host, port)) {
    delete c;
    return nullptr;
  }
  return c;
}
void pt_ps_disconnect(void* h) { delete (Client*)h; }
const char* pt_ps_client_error(void* h) {
  return ((Client*)h)->error.c_str();
}

static thread_local std::vector<char> g_resp;

// surface the server's Err string (payload after kErr status) to callers
static void CaptureServerError(Client* c) {
  if (g_resp.size() >= 3) {
    uint16_t nl = 0;
    memcpy(&nl, g_resp.data() + 1, 2);
    if (3 + (size_t)nl <= g_resp.size()) {
      c->error.assign(g_resp.data() + 3, nl);
      return;
    }
  }
  c->error = "server returned error (no detail)";
}

static int SimpleCall(Client* c, Writer& w) {
  if (!c->Call(w, &g_resp)) return -1;
  if (!g_resp.empty() && g_resp[0] == 0) return 0;
  CaptureServerError(c);
  return -2;
}

int pt_ps_init_dense(void* h, const char* name, const float* data,
                     uint64_t n) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kInitDense);
  w.Str(name);
  w.Put<uint64_t>(n);
  w.Raw(data, n * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_push_dense(void* h, const char* name, const float* grad,
                     uint64_t n, int optimize) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPushDense);
  w.Str(name);
  w.Put<uint8_t>((uint8_t)(optimize ? 1 : 0));
  w.Put<uint64_t>(n);
  w.Raw(grad, n * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_pull_dense(void* h, const char* name, float* out, uint64_t n) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullDense);
  w.Str(name);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 9) {
    c->error = "pull_dense: truncated response header";
    return -4;
  }
  uint64_t count = 0;
  memcpy(&count, g_resp.data() + 1, 8);
  if (count != n) {
    c->error = "pull_dense size mismatch: server has " +
               std::to_string(count) + ", caller expects " +
               std::to_string(n);
    return -3;
  }
  if (g_resp.size() < 9 + (uint64_t)n * 4) {
    c->error = "pull_dense: truncated response payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 9, n * 4);
  return 0;
}

int pt_ps_pull_dense_if_newer(void* h, const char* name, float* out,
                              uint64_t n, uint64_t* version_io) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullDenseIfNewer);
  w.Str(name);
  w.Put<uint64_t>(*version_io);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 10) {
    c->error = "pull_dense_if_newer: truncated header";
    return -4;
  }
  uint64_t ver = 0;
  memcpy(&ver, g_resp.data() + 1, 8);
  uint8_t has = (uint8_t)g_resp[9];
  *version_io = ver;
  if (!has) return 1;  // unchanged: no payload transferred
  if (g_resp.size() < 18) {
    c->error = "pull_dense_if_newer: truncated count";
    return -4;
  }
  uint64_t count = 0;
  memcpy(&count, g_resp.data() + 10, 8);
  if (count != n) {
    c->error = "pull_dense_if_newer size mismatch";
    return -3;
  }
  if (g_resp.size() < 18 + (uint64_t)n * 4) {
    c->error = "pull_dense_if_newer: truncated payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 18, n * 4);
  return 0;
}

int pt_ps_push_sparse(void* h, const char* table, uint32_t dim,
                      const int64_t* keys, uint64_t n, const float* grads) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPushSparse);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  w.Raw(grads, (uint64_t)n * dim * 4);
  return SimpleCall((Client*)h, w);
}

int pt_ps_pull_sparse(void* h, const char* table, uint32_t dim,
                      const int64_t* keys, uint64_t n, float* out) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kPullSparse);
  w.Str(table);
  w.Put<uint32_t>(dim);
  w.Put<uint64_t>(n);
  w.Raw(keys, n * 8);
  Client* c = (Client*)h;
  if (!c->Call(w, &g_resp)) return -1;
  if (g_resp.empty() || g_resp[0] != 0) {
    CaptureServerError(c);
    return -2;
  }
  if (g_resp.size() < 9 + (uint64_t)n * dim * 4) {
    c->error = "pull_sparse: truncated response payload";
    return -4;
  }
  memcpy(out, g_resp.data() + 9, (uint64_t)n * dim * 4);
  return 0;
}

int pt_ps_barrier(void* h, uint32_t barrier_id) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kBarrier);
  w.Put<uint32_t>(barrier_id);
  return SimpleCall((Client*)h, w);
}

int pt_ps_heartbeat(void* h, uint32_t trainer_id) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kHeartbeat);
  w.Put<uint32_t>(trainer_id);
  return SimpleCall((Client*)h, w);
}

int pt_ps_shutdown(void* h) {
  Writer w;
  w.Put<uint8_t>(ptcore::ps::kShutdown);
  return SimpleCall((Client*)h, w);
}

}  // extern "C"
