// Pure-C++ training entry (reference parity:
// paddle/fluid/train/test_train_recognize_digits.cc — train a saved
// recognize-digits program with NO Python in the loop).
//
// Usage: train_demo <model_dir> [steps]
//
// Loads the training artifact (save_train_model: __model__ keeps the
// jax_autodiff backward + sgd ops), generates a learnable synthetic
// digit batch in C++ (class k lights a kx2-offset block in a 28x28
// image + noise), runs `steps` training iterations through the native
// executor's grad-kernel registry, and exits 0 iff the fetched loss
// fell to < 1/3 of the first step's. Only the flat C ABI is used —
// this file compiles against libptcore.so with no other headers.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {
void* pt_pred_create(const char* model_dir);
const char* pt_pred_error(void* h);
void pt_pred_set_input(void* h, const char* name, const int64_t* dims,
                       int ndim, const float* data);
void pt_pred_set_input_i64(void* h, const char* name, const int64_t* dims,
                           int ndim, const int64_t* data);
int pt_pred_run(void* h);
int pt_pred_out_ndim(void* h, int i);
void pt_pred_out_dims(void* h, int i, int64_t* out);
void pt_pred_out_copy(void* h, int i, void* out);
void pt_pred_destroy(void* h);
}

static uint64_t rng_state = 0x9E3779B97F4A7C15ull;
static float frand() {  // xorshift uniform in [0, 1)
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return (float)((rng_state >> 11) & 0xFFFFFF) / 16777216.0f;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: train_demo <model_dir> [steps]\n");
    return 2;
  }
  int steps = argc > 2 ? std::atoi(argv[2]) : 30;
  void* h = pt_pred_create(argv[1]);
  const char* err = pt_pred_error(h);
  if (err && err[0]) {
    std::fprintf(stderr, "load failed: %s\n", err);
    return 2;
  }
  const int B = 32, C = 10, HW = 28;
  std::vector<float> img((size_t)B * HW * HW);
  std::vector<int64_t> lbl(B);
  int64_t idims[4] = {B, 1, HW, HW};
  int64_t ldims[2] = {B, 1};
  float first = -1.0f, last = -1.0f;
  for (int s = 0; s < steps; ++s) {
    for (int b = 0; b < B; ++b) {
      int cls = (int)(frand() * C) % C;
      lbl[b] = cls;
      float* im = &img[(size_t)b * HW * HW];
      for (int k = 0; k < HW * HW; ++k) im[k] = 0.1f * frand();
      // class signature: a bright 6x6 block at a class-specific spot
      int r0 = 2 + (cls / 5) * 12, c0 = 2 + (cls % 5) * 5;
      for (int r = r0; r < r0 + 6 && r < HW; ++r)
        for (int cc = c0; cc < c0 + 6 && cc < HW; ++cc)
          im[r * HW + cc] = 0.9f + 0.1f * frand();
    }
    pt_pred_set_input(h, "img", idims, 4, img.data());
    pt_pred_set_input_i64(h, "label", ldims, 2, lbl.data());
    if (pt_pred_run(h) != 0) {
      std::fprintf(stderr, "step %d failed: %s\n", s, pt_pred_error(h));
      return 2;
    }
    float loss = 0.0f;
    pt_pred_out_copy(h, 0, &loss);
    if (s == 0) first = loss;
    last = loss;
    if (s % 10 == 0 || s == steps - 1)
      std::printf("step %d loss %.4f\n", s, loss);
  }
  pt_pred_destroy(h);
  std::printf("first %.4f last %.4f\n", first, last);
  if (!(last < first / 3.0f)) {
    std::fprintf(stderr, "loss did not decrease enough\n");
    return 1;
  }
  return 0;
}
