// Native tensor (de)serialization.
//
// Capability parity with the reference's framework/save_load_util.cc and
// the save/save_combine/load/load_combine ops — own format ("PTT1"):
//   [magic u32][dtype u8][ndim u8][dims i64 * ndim][nbytes u64][raw data]
// Combine files ("PTC1") hold an entry count then (name_len u16, name,
// tensor record) sequences, so a whole state dict round-trips in one file.
#include "saveload.h"

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace ptcore {

static const uint32_t kTensorMagic = 0x50545431;  // "PTT1"
static const uint32_t kCombineMagic = 0x50544331;  // "PTC1"

static bool WriteTensorRecord(FILE* f, uint8_t dtype, const int64_t* dims,
                              int ndim, const void* data, uint64_t nbytes) {
  uint32_t magic = kTensorMagic;
  uint8_t nd = (uint8_t)ndim;
  if (fwrite(&magic, 4, 1, f) != 1) return false;
  if (fwrite(&dtype, 1, 1, f) != 1) return false;
  if (fwrite(&nd, 1, 1, f) != 1) return false;
  if (ndim && fwrite(dims, 8, ndim, f) != (size_t)ndim) return false;
  if (fwrite(&nbytes, 8, 1, f) != 1) return false;
  if (nbytes && fwrite(data, 1, nbytes, f) != nbytes) return false;
  return true;
}

static bool ReadTensorRecord(FILE* f, HostTensor* t) {
  uint32_t magic = 0;
  if (fread(&magic, 4, 1, f) != 1 || magic != kTensorMagic) return false;
  uint8_t nd = 0;
  if (fread(&t->dtype, 1, 1, f) != 1) return false;
  if (fread(&nd, 1, 1, f) != 1) return false;
  t->dims.resize(nd);
  if (nd && fread(t->dims.data(), 8, nd, f) != nd) return false;
  uint64_t nbytes = 0;
  if (fread(&nbytes, 8, 1, f) != 1) return false;
  t->data.resize(nbytes);
  if (nbytes && fread(t->data.data(), 1, nbytes, f) != nbytes) return false;
  return true;
}

bool SaveTensorFile(const char* path, uint8_t dtype, const int64_t* dims,
                    int ndim, const void* data, uint64_t nbytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return false;
  bool ok = WriteTensorRecord(f, dtype, dims, ndim, data, nbytes);
  fclose(f);
  return ok;
}

bool LoadTensorFile(const char* path, HostTensor* t) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  bool ok = ReadTensorRecord(f, t);
  fclose(f);
  return ok;
}

struct CombineWriter {
  FILE* f = nullptr;
  uint64_t count = 0;
};

CombineWriter* CombineOpen(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  uint32_t magic = kCombineMagic;
  uint64_t zero = 0;
  fwrite(&magic, 4, 1, f);
  fwrite(&zero, 8, 1, f);  // patched at close
  auto* w = new CombineWriter;
  w->f = f;
  return w;
}

bool CombineAdd(CombineWriter* w, const char* name, uint8_t dtype,
                const int64_t* dims, int ndim, const void* data,
                uint64_t nbytes) {
  uint16_t nl = (uint16_t)strlen(name);
  if (fwrite(&nl, 2, 1, w->f) != 1) return false;
  if (fwrite(name, 1, nl, w->f) != nl) return false;
  if (!WriteTensorRecord(w->f, dtype, dims, ndim, data, nbytes)) return false;
  w->count++;
  return true;
}

bool CombineClose(CombineWriter* w) {
  fseek(w->f, 4, SEEK_SET);
  bool ok = fwrite(&w->count, 8, 1, w->f) == 1;
  fclose(w->f);
  delete w;
  return ok;
}

CombineReader* CombineLoad(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  uint32_t magic = 0;
  uint64_t count = 0;
  if (fread(&magic, 4, 1, f) != 1 || magic != kCombineMagic ||
      fread(&count, 8, 1, f) != 1) {
    fclose(f);
    return nullptr;
  }
  auto* r = new CombineReader;
  r->complete = true;
  for (uint64_t i = 0; i < count; ++i) {
    uint16_t nl = 0;
    if (fread(&nl, 2, 1, f) != 1) {
      r->complete = false;
      break;
    }
    std::string name(nl, 0);
    if (nl && fread(&name[0], 1, nl, f) != nl) {
      r->complete = false;
      break;
    }
    HostTensor t;
    if (!ReadTensorRecord(f, &t)) {
      r->complete = false;
      break;
    }
    r->entries.emplace_back(std::move(name), std::move(t));
  }
  fclose(f);
  return r;
}

}  // namespace ptcore
