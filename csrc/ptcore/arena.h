// Host staging-buffer arena: best-fit free-list allocator with chunked
// growth. Capability parity with the reference's
// memory/allocation/auto_growth_best_fit_allocator.h — on TPU, XLA owns
// device HBM, so the native allocator's surviving job is host-side staging
// buffers (feed batches, checkpoint IO) with low fragmentation and stats.
#pragma once

#include <cstdint>
#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace ptcore {

class Arena {
 public:
  explicit Arena(size_t chunk_bytes = 64 << 20, size_t alignment = 64)
      : chunk_(0), align_(alignment ? alignment : 64) {
    // aligned_alloc requires size to be a multiple of alignment
    chunk_ = RoundUp(chunk_bytes ? chunk_bytes : align_);
  }
  ~Arena() {
    for (void* c : chunks_) std::free(c);
  }

  void* Alloc(size_t n) {
    std::lock_guard<std::mutex> lk(mu_);
    n = RoundUp(n ? n : 1);  // size-0 allocs get a real block: a zero-size
                             // best-fit would re-free the block it returns
    auto it = free_.lower_bound(n);  // best fit: smallest block >= n
    if (it == free_.end()) {
      Grow(n);
      it = free_.lower_bound(n);
      if (it == free_.end()) return nullptr;  // OOM: Grow failed
    }
    size_t bsz = it->first;
    char* p = it->second;
    free_.erase(it);
    if (bsz > n + align_) {  // split remainder back to free list
      free_.emplace(bsz - n, p + n);
      bsz = n;
    }
    live_[p] = bsz;
    in_use_ += bsz;
    peak_ = in_use_ > peak_ ? in_use_ : peak_;
    return p;
  }

  void Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = live_.find((char*)p);
    if (it == live_.end()) return;
    in_use_ -= it->second;
    free_.emplace(it->second, it->first);
    live_.erase(it);
  }

  size_t InUse() const { return in_use_; }
  size_t Peak() const { return peak_; }
  size_t Reserved() const { return reserved_; }

 private:
  size_t RoundUp(size_t n) const { return (n + align_ - 1) / align_ * align_; }
  void Grow(size_t need) {
    size_t sz = need > chunk_ ? RoundUp(need) : chunk_;
    void* c = std::aligned_alloc(align_, sz);
    if (!c) return;  // OOM surfaces as Alloc() -> nullptr
    chunks_.push_back(c);
    reserved_ += sz;
    free_.emplace(sz, (char*)c);
  }

  std::mutex mu_;
  size_t chunk_, align_;
  std::multimap<size_t, char*> free_;
  std::unordered_map<char*, size_t> live_;
  std::vector<void*> chunks_;
  size_t in_use_ = 0, peak_ = 0, reserved_ = 0;
};

}  // namespace ptcore
