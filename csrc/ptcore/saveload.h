#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace ptcore {

struct HostTensor {
  uint8_t dtype = 0;
  std::vector<int64_t> dims;
  std::vector<char> data;
};

bool SaveTensorFile(const char* path, uint8_t dtype, const int64_t* dims,
                    int ndim, const void* data, uint64_t nbytes);
bool LoadTensorFile(const char* path, HostTensor* t);

struct CombineWriter;
CombineWriter* CombineOpen(const char* path);
bool CombineAdd(CombineWriter* w, const char* name, uint8_t dtype,
                const int64_t* dims, int ndim, const void* data,
                uint64_t nbytes);
bool CombineClose(CombineWriter* w);

struct CombineReader {
  std::vector<std::pair<std::string, HostTensor>> entries;
  bool complete = false;  // all declared entries read back intact
};
CombineReader* CombineLoad(const char* path);

}  // namespace ptcore
