#include "datafeed.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace ptcore {

DataFeed::DataFeed(std::vector<SlotConf> slots, int num_threads,
                   size_t queue_cap)
    : slots_(std::move(slots)),
      num_threads_(num_threads > 0 ? num_threads : 1),
      file_q_(1 << 20),
      record_q_(queue_cap),
      batch_q_(8) {
  for (const auto& s : slots_) (s.is_float ? nf_ : ni_)++;
}

DataFeed::~DataFeed() { Stop(); }

void DataFeed::AddFile(const std::string& path) { files_.push_back(path); }

void DataFeed::Start(int batch_size, int64_t shuffle_buf, uint64_t seed) {
  Stop();
  file_q_.Reopen();
  record_q_.Reopen();
  batch_q_.Reopen();
  samples_seen_ = 0;
  {
    std::lock_guard<std::mutex> lk(err_mu_);
    has_error_.store(false, std::memory_order_release);
    error_.clear();
  }
  for (const auto& f : files_) {
    std::string copy = f;
    file_q_.Push(std::move(copy));
  }
  file_q_.Close();  // parsers drain then exit
  live_parsers_ = num_threads_;
  parsers_.clear();
  for (int i = 0; i < num_threads_; ++i)
    parsers_.emplace_back([this] { ParseWorker(); });
  assembler_ = std::thread([this, batch_size, shuffle_buf, seed] {
    AssembleWorker(batch_size, shuffle_buf, seed);
  });
  started_ = true;
}

void DataFeed::Stop() {
  if (!started_) return;
  file_q_.Close();
  record_q_.Close();
  batch_q_.Close();
  for (auto& t : parsers_)
    if (t.joinable()) t.join();
  if (assembler_.joinable()) assembler_.join();
  parsers_.clear();
  started_ = false;
}

std::unique_ptr<Batch> DataFeed::Next() {
  std::unique_ptr<Batch> b;
  if (!batch_q_.Pop(&b)) return nullptr;
  return b;
}

bool DataFeed::ParseLine(const char* p, size_t len, Record* rec) {
  const char* end = p + len;
  rec->fvals.assign(nf_, {});
  rec->ivals.assign(ni_, {});
  int fi = 0, ii = 0;
  for (const auto& slot : slots_) {
    char* next = nullptr;
    long n = strtol(p, &next, 10);
    if (next == p || n < 0) return false;
    p = next;
    if (slot.dense_dim > 0 && n != slot.dense_dim) return false;
    if (slot.is_float) {
      auto& v = rec->fvals[fi++];
      v.reserve(n);
      for (long k = 0; k < n; ++k) {
        float x = strtof(p, &next);
        if (next == p) return false;
        v.push_back(x);
        p = next;
      }
    } else {
      auto& v = rec->ivals[ii++];
      v.reserve(n);
      for (long k = 0; k < n; ++k) {
        long long x = strtoll(p, &next, 10);
        if (next == p) return false;
        v.push_back((int64_t)x);
        p = next;
      }
    }
    if (p > end) return false;
  }
  return true;
}

void DataFeed::SetError(std::string msg) {
  std::lock_guard<std::mutex> lk(err_mu_);
  if (has_error_.load(std::memory_order_relaxed)) return;  // first error wins
  error_ = std::move(msg);
  has_error_.store(true, std::memory_order_release);
}

static const char kBinMagic[5] = {'P', 'T', 'M', 'B', 1};

bool DataFeed::ParseBinaryFile(FILE* f, const std::string& path) {
  // binary MultiSlot wire (data_feed.h:650 in-memory/protobin role):
  // magic "PTMB\x01" | per record: u8 0xAB | per slot in conf order:
  // u32 count | count x (f32 | i64). Strict: any framing error poisons
  // the feed instead of silently skipping records.
  while (true) {
    uint8_t sent = 0;
    size_t got = fread(&sent, 1, 1, f);
    if (got != 1) return true;  // clean EOF
    if (sent != 0xAB) {
      SetError("protobin: bad record sentinel in " + path);
      return false;
    }
    Record rec;
    rec.fvals.assign(nf_, {});
    rec.ivals.assign(ni_, {});
    int fi = 0, ii = 0;
    for (const auto& slot : slots_) {
      uint32_t n = 0;
      if (fread(&n, 4, 1, f) != 1 || n > (64u << 20)) {
        SetError("protobin: truncated/oversized slot in " + path);
        return false;
      }
      if (slot.dense_dim > 0 && n != (uint32_t)slot.dense_dim) {
        SetError("protobin: dense dim mismatch in " + path);
        return false;
      }
      if (slot.is_float) {
        auto& v = rec.fvals[fi++];
        v.resize(n);
        if (n && fread(v.data(), 4, n, f) != n) {
          SetError("protobin: truncated payload in " + path);
          return false;
        }
      } else {
        auto& v = rec.ivals[ii++];
        v.resize(n);
        if (n && fread(v.data(), 8, n, f) != n) {
          SetError("protobin: truncated payload in " + path);
          return false;
        }
      }
    }
    if (!record_q_.Push(std::move(rec))) return true;  // stopped
    samples_seen_++;
  }
}

void DataFeed::ParseWorker() {
  std::string path;
  while (file_q_.Pop(&path)) {
    FILE* f = nullptr;
    // "cmd |" prefix runs a shell producer (the reference reads HDFS via
    // forked pipes — framework/io/shell.cc); plain paths are fopen'd.
    bool pipe = path.size() > 1 && path.back() == '|';
    if (pipe) {
      std::string cmd = path.substr(0, path.size() - 1);
      f = popen(cmd.c_str(), "r");
    } else {
      f = fopen(path.c_str(), "rb");
    }
    if (!f) {
      SetError("open failed: " + path);
      continue;
    }
    // SEEKABLE regular files sniff the binary magic; pipes and
    // non-seekable paths (FIFOs, /dev/fd/N) stay text — sniffing them
    // would eat the first bytes with no way to rewind
    if (!pipe && ftell(f) == 0) {
      char head[5] = {0};
      size_t got = fread(head, 1, 5, f);
      if (got == 5 && memcmp(head, kBinMagic, 5) == 0) {
        ParseBinaryFile(f, path);
        fclose(f);
        continue;
      }
      if (fseek(f, 0, SEEK_SET) != 0) {
        SetError("datafeed: cannot rewind after sniff: " + path);
        fclose(f);
        continue;
      }
    }
    char* line = nullptr;
    size_t cap = 0;
    ssize_t got;
    while ((got = getline(&line, &cap, f)) > 0) {
      Record rec;
      if (ParseLine(line, (size_t)got, &rec)) {
        if (!record_q_.Push(std::move(rec))) break;  // stopped
        samples_seen_++;
      }
    }
    free(line);
    if (pipe)
      pclose(f);
    else
      fclose(f);
  }
  if (--live_parsers_ == 0) record_q_.Close();
}

void DataFeed::AssembleWorker(int batch_size, int64_t shuffle_buf,
                              uint64_t seed) {
  std::mt19937_64 rng(seed);
  std::vector<Record> buf;  // shuffle reservoir
  std::vector<Record> pending;
  auto emit = [&](std::vector<Record>& rs) -> bool {
    if (rs.empty()) return true;
    auto b = std::make_unique<Batch>();
    b->batch_size = (int64_t)rs.size();
    b->fvals.assign(nf_, {});
    b->ivals.assign(ni_, {});
    b->offsets.assign(slots_.size(), std::vector<int64_t>{0});
    for (auto& r : rs) {
      int fi = 0, ii = 0, si = 0;
      for (const auto& slot : slots_) {
        if (slot.is_float) {
          auto& src = r.fvals[fi];
          auto& dst = b->fvals[fi];
          dst.insert(dst.end(), src.begin(), src.end());
          b->offsets[si].push_back((int64_t)dst.size());
          fi++;
        } else {
          auto& src = r.ivals[ii];
          auto& dst = b->ivals[ii];
          dst.insert(dst.end(), src.begin(), src.end());
          b->offsets[si].push_back((int64_t)dst.size());
          ii++;
        }
        si++;
      }
    }
    rs.clear();
    return batch_q_.Push(std::move(b));
  };

  Record rec;
  while (record_q_.Pop(&rec)) {
    if (shuffle_buf > 0) {
      if ((int64_t)buf.size() < shuffle_buf) {
        buf.push_back(std::move(rec));
        continue;
      }
      // swap a random reservoir slot out into the pending batch
      size_t j = rng() % buf.size();
      pending.push_back(std::move(buf[j]));
      buf[j] = std::move(rec);
    } else {
      pending.push_back(std::move(rec));
    }
    if ((int)pending.size() == batch_size) {
      if (!emit(pending)) return;
    }
  }
  // drain reservoir (shuffled)
  for (size_t i = buf.size(); i > 1; --i)
    std::swap(buf[i - 1], buf[rng() % i]);
  for (auto& r : buf) {
    pending.push_back(std::move(r));
    if ((int)pending.size() == batch_size)
      if (!emit(pending)) return;
  }
  emit(pending);
  batch_q_.Close();
}

}  // namespace ptcore
