// Native inference executor.
//
// Capability parity with the reference's NaiveExecutor
// (framework/naive_executor.h) + AnalysisPredictor C core
// (inference/api/analysis_predictor.cc:288 Run): loads a ProgramDesc proto
// (`__model__`, csrc/proto/ptframework.proto) and a combined params file
// (`__params__`, PTC1), then interprets the op list with a small CPU
// kernel registry — the no-Python deployment path (the XLA path is the
// fast one; this is the standalone C ABI predictor, serving the role of
// paddle/fluid/train's pure-C++ entry and the inference C API).
#include <algorithm>
#include <cmath>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "ptframework.pb.h"
#include "saveload.h"

namespace ptcore {

struct NTensor {
  std::vector<int64_t> dims;
  std::vector<float> f;    // float32 storage
  std::vector<int64_t> i;  // int64 storage
  std::vector<int8_t> q;   // int8 storage (slim PTQ/QAT weights)
  bool is_int = false;
  bool is_q = false;

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
};

struct ExecCtx {
  std::unordered_map<std::string, NTensor> vars;  // activations (per run)
  const std::unordered_map<std::string, NTensor>* params = nullptr;
  const ptframework::OpDesc* op = nullptr;
  std::string error;

  // inputs resolve activations first, then read-only params — avoids
  // copying the whole weight map every Run (kernels never write params)
  NTensor* In(const std::string& slot, int idx = 0) {
    for (const auto& s : op->inputs())
      if (s.name() == slot && idx < s.args_size()) {
        const std::string& n = s.args(idx);
        auto it = vars.find(n);
        if (it != vars.end()) return &it->second;
        if (params) {
          auto pit = params->find(n);
          if (pit != params->end())
            return const_cast<NTensor*>(&pit->second);
        }
        error = "input var not set: " + n;
        return nullptr;
      }
    return nullptr;
  }
  NTensor* Out(const std::string& slot, int idx = 0) {
    for (const auto& s : op->outputs())
      if (s.name() == slot && idx < s.args_size())
        return &vars[s.args(idx)];
    return nullptr;
  }
  const ptframework::Attr* FindAttr(const std::string& name) {
    for (const auto& a : op->attrs())
      if (a.name() == name) return &a;
    return nullptr;
  }
  int64_t AttrI(const std::string& n, int64_t dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kI ? a->i() : dflt;
  }
  double AttrF(const std::string& n, double dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kF ? a->f() : dflt;
  }
  bool AttrB(const std::string& n, bool dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kB ? a->b() : dflt;
  }
  std::string AttrS(const std::string& n, const std::string& dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kS ? a->s() : dflt;
  }
  std::vector<int64_t> AttrInts(const std::string& n) {
    auto* a = FindAttr(n);
    std::vector<int64_t> out;
    if (a && a->value_case() == ptframework::Attr::kInts)
      for (auto v : a->ints().val()) out.push_back(v);
    return out;
  }
  std::vector<double> AttrFloats(const std::string& n) {
    auto* a = FindAttr(n);
    std::vector<double> out;
    if (a && a->value_case() == ptframework::Attr::kFloats)
      for (auto v : a->floats().val()) out.push_back(v);
    return out;
  }
};

using Kernel = std::function<bool(ExecCtx&)>;

static std::map<std::string, Kernel>& Registry() {
  static std::map<std::string, Kernel> r;
  return r;
}

struct RegK {
  RegK(const char* name, Kernel k) { Registry()[name] = std::move(k); }
};

// ---------------- kernels ----------------

static bool EwiseUnary(ExecCtx& c, float (*fn)(float)) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = fn(x->f[k]);
  return true;
}

static RegK r_relu("relu", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return v > 0 ? v : 0.0f; });
});
static RegK r_sigmoid("sigmoid", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return 1.0f / (1.0f + expf(-v)); });
});
static RegK r_tanh("tanh", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return tanhf(v); });
});

static RegK r_scale("scale", [](ExecCtx& c) {
  float s = (float)c.AttrF("scale", 1.0);
  float b = (float)c.AttrF("bias", 0.0);
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = x->f[k] * s + b;
  return true;
});

static RegK r_dropout("dropout", [](ExecCtx& c) {  // inference: identity
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  *o = *x;
  return true;
});

// reshape/flatten/squeeze/unsqueeze: raw data carryover, dims recomputed.
// shape entry 0 = copy input dim at that index (fluid semantics, matching
// the Python lowering); -1 = infer.
static bool Reshape(ExecCtx& c, std::vector<int64_t> shape) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  int64_t known = 1, infer = -1;
  for (size_t k = 0; k < shape.size(); ++k) {
    if (shape[k] == 0) {
      if (k >= x->dims.size()) {
        c.error = "reshape: 0-dim index out of range";
        return false;
      }
      shape[k] = x->dims[k];
    }
    if (shape[k] == -1) {
      infer = (int64_t)k;
    } else {
      known *= shape[k];
    }
  }
  if (infer >= 0) shape[infer] = x->numel() / (known ? known : 1);
  o->f = x->f;
  o->i = x->i;
  o->is_int = x->is_int;
  o->dims = shape;
  return true;
}

static RegK r_reshape("reshape", [](ExecCtx& c) {
  return Reshape(c, c.AttrInts("shape"));
});
static RegK r_flatten("flatten", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  int64_t ax = c.AttrI("axis", 1);
  int64_t d0 = 1, d1 = 1;
  for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
    (k < ax ? d0 : d1) *= x->dims[k];
  return Reshape(c, {d0, d1});
});

static RegK r_mul("mul", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  int64_t xcols = c.AttrI("x_num_col_dims", 1);
  int64_t M = 1, K = 1;
  for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
    (k < xcols ? M : K) *= x->dims[k];
  int64_t K2 = y->dims[0], N = y->numel() / y->dims[0];
  if (K != K2) {
    c.error = "mul: K mismatch";
    return false;
  }
  o->dims.assign(x->dims.begin(), x->dims.begin() + xcols);
  o->dims.push_back(N);
  o->f.assign(M * N, 0.0f);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t k = 0; k < K; ++k) {
      float xv = x->f[m * K + k];
      const float* yr = &y->f[k * N];
      float* orow = &o->f[m * N];
      for (int64_t n = 0; n < N; ++n) orow[n] += xv * yr[n];
    }
  return true;
});

static RegK r_matmul("matmul", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  bool tx = c.AttrB("transpose_X", false), ty = c.AttrB("transpose_Y", false);
  float alpha = (float)c.AttrF("alpha", 1.0);
  if (x->dims.size() != 2 || y->dims.size() != 2) {
    c.error = "matmul: only 2D supported in native predictor";
    return false;
  }
  int64_t M = tx ? x->dims[1] : x->dims[0];
  int64_t K = tx ? x->dims[0] : x->dims[1];
  int64_t N = ty ? y->dims[0] : y->dims[1];
  o->dims = {M, N};
  o->f.assign(M * N, 0.0f);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t k = 0; k < K; ++k) {
      float xv = tx ? x->f[k * M + m] : x->f[m * K + k];
      for (int64_t n = 0; n < N; ++n) {
        float yv = ty ? y->f[n * K + k] : y->f[k * N + n];
        o->f[m * N + n] += alpha * xv * yv;
      }
    }
  return true;
});

static RegK r_eadd("elementwise_add", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  if (x->dims == y->dims) {
    for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = x->f[k] + y->f[k];
    return true;
  }
  // broadcast Y along `axis` (bias pattern): Y dims match
  // x.dims[axis:axis+y.ndim]
  int64_t axis = c.AttrI("axis", -1);
  if (axis < 0) axis = (int64_t)x->dims.size() - (int64_t)y->dims.size();
  int64_t pre = 1, mid = y->numel(), post = 1;
  for (int64_t k = 0; k < axis; ++k) pre *= x->dims[k];
  for (int64_t k = axis + (int64_t)y->dims.size();
       k < (int64_t)x->dims.size(); ++k)
    post *= x->dims[k];
  if (pre * mid * post != x->numel()) {
    c.error = "elementwise_add: bad broadcast";
    return false;
  }
  for (int64_t p = 0; p < pre; ++p)
    for (int64_t m = 0; m < mid; ++m)
      for (int64_t q = 0; q < post; ++q) {
        int64_t idx = (p * mid + m) * post + q;
        o->f[idx] = x->f[idx] + y->f[m];
      }
  return true;
});

static RegK r_softmax("softmax", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  int64_t last = x->dims.back();
  int64_t rows = x->numel() / last;
  for (int64_t r = 0; r < rows; ++r) {
    const float* xr = &x->f[r * last];
    float* orow = &o->f[r * last];
    float mx = xr[0];
    for (int64_t k = 1; k < last; ++k) mx = std::max(mx, xr[k]);
    float sum = 0;
    for (int64_t k = 0; k < last; ++k) {
      orow[k] = expf(xr[k] - mx);
      sum += orow[k];
    }
    for (int64_t k = 0; k < last; ++k) orow[k] /= sum;
  }
  return true;
});

static RegK r_conv2d("conv2d", [](ExecCtx& c) {
  NTensor* x = c.In("Input");
  NTensor* w = c.In("Filter");
  NTensor* o = c.Out("Output");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  auto dil = c.AttrInts("dilations");
  int64_t g = c.AttrI("groups", 1);
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (dil.empty()) dil = {1, 1};
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t OC = w->dims[0], KC = w->dims[1], KH = w->dims[2], KW = w->dims[3];
  int64_t OH = (H + 2 * pads[0] - dil[0] * (KH - 1) - 1) / strides[0] + 1;
  int64_t OW = (W + 2 * pads[1] - dil[1] * (KW - 1) - 1) / strides[1] + 1;
  o->dims = {N, OC, OH, OW};
  o->f.assign(N * OC * OH * OW, 0.0f);
  int64_t cpg = C / g, opg = OC / g;
  for (int64_t n = 0; n < N; ++n)
    for (int64_t oc = 0; oc < OC; ++oc) {
      int64_t grp = oc / opg;
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float acc = 0;
          for (int64_t ic = 0; ic < cpg; ++ic) {
            int64_t cin = grp * cpg + ic;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                if (iw < 0 || iw >= W) continue;
                acc += x->f[((n * C + cin) * H + ih) * W + iw] *
                       w->f[((oc * KC + ic) * KH + kh) * KW + kw];
              }
            }
          }
          o->f[((n * OC + oc) * OH + oh) * OW + ow] = acc;
        }
    }
  return true;
});

static RegK r_pool2d("pool2d", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  auto ksize = c.AttrInts("ksize");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  bool global = c.AttrB("global_pooling", false);
  bool exclusive = c.AttrB("exclusive", true);
  std::string type = c.AttrS("pooling_type", "max");
  bool adaptive = c.AttrB("adaptive", false);
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (global) {
    ksize = {H, W};
    strides = {H, W};
    pads = {0, 0};
  }
  int64_t OH, OW;
  if (adaptive) {
    OH = ksize[0];
    OW = ksize[1];
  } else {
    OH = (H + 2 * pads[0] - ksize[0]) / strides[0] + 1;
    OW = (W + 2 * pads[1] - ksize[1]) / strides[1] + 1;
  }
  o->dims = {N, C, OH, OW};
  o->f.assign(N * C * OH * OW, 0.0f);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t ch = 0; ch < C; ++ch)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          int64_t h0, h1, w0, w1;
          if (adaptive) {
            h0 = oh * H / OH;
            h1 = (oh + 1) * H / OH;
            w0 = ow * W / OW;
            w1 = (ow + 1) * W / OW;
          } else {
            h0 = oh * strides[0] - pads[0];
            h1 = std::min(h0 + ksize[0], H);
            w0 = ow * strides[1] - pads[1];
            w1 = std::min(w0 + ksize[1], W);
            h0 = std::max<int64_t>(h0, 0);
            w0 = std::max<int64_t>(w0, 0);
          }
          float acc = type == "max" ? -3.4e38f : 0.0f;
          int64_t cnt = 0;
          for (int64_t ih = h0; ih < h1; ++ih)
            for (int64_t iw = w0; iw < w1; ++iw) {
              float v = x->f[((n * C + ch) * H + ih) * W + iw];
              if (type == "max")
                acc = std::max(acc, v);
              else
                acc += v;
              cnt++;
            }
          if (type != "max")
            acc /= exclusive ? (float)cnt
                             : (float)(ksize[0] * ksize[1]);
          o->f[((n * C + ch) * OH + oh) * OW + ow] = acc;
        }
  return true;
});

// depthwise_conv2d is conv2d with groups == channels; the grouped conv
// kernel above already handles it (filter [OC, 1, KH, KW])
static RegK r_dwconv("depthwise_conv2d", [](ExecCtx& c) {
  return Registry()["conv2d"](c);
});

static RegK r_relu6("relu6", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) {
    return v < 0 ? 0.0f : (v > 6.0f ? 6.0f : v);
  });
});

// MobileNetV3-family activations (hard_sigmoid/hard_swish)
static RegK r_hsig("hard_sigmoid", [](ExecCtx& c) {
  float slope = (float)c.AttrF("slope", 0.2);
  float offset = (float)c.AttrF("offset", 0.5);
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) {
    float y = x->f[k] * slope + offset;
    o->f[k] = y < 0 ? 0.0f : (y > 1.0f ? 1.0f : y);
  }
  return true;
});
static RegK r_hswish("hard_swish", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) {
    float t = v + 3.0f;
    t = t < 0 ? 0.0f : (t > 6.0f ? 6.0f : t);
    return v * t / 6.0f;
  });
});

static int64_t NormAxis(int64_t axis, size_t ndim) {
  return axis < 0 ? axis + (int64_t)ndim : axis;
}

static RegK r_concat("concat", [](ExecCtx& c) {
  // gather the X arg list
  std::vector<NTensor*> xs;
  for (const auto& s : c.op->inputs())
    if (s.name() == "X")
      for (int k = 0; k < s.args_size(); ++k) {
        NTensor* t = c.In("X", k);
        if (!t) return false;
        xs.push_back(t);
      }
  if (xs.empty()) {
    c.error = "concat: no inputs";
    return false;
  }
  NTensor* o = c.Out("Out");
  int64_t axis = NormAxis(c.AttrI("axis", 0), xs[0]->dims.size());
  if (axis < 0 || axis >= (int64_t)xs[0]->dims.size()) {
    c.error = "concat: bad axis";
    return false;
  }
  // every input must share rank and non-axis dims (and float storage:
  // the int64 path isn't wired here)
  for (auto* t : xs) {
    if (t->is_int) {
      c.error = "concat: int tensors unsupported in native engine";
      return false;
    }
    if (t->dims.size() != xs[0]->dims.size()) {
      c.error = "concat: rank mismatch";
      return false;
    }
    for (size_t k = 0; k < t->dims.size(); ++k)
      if ((int64_t)k != axis && t->dims[k] != xs[0]->dims[k]) {
        c.error = "concat: non-axis dim mismatch";
        return false;
      }
  }
  int64_t pre = 1, post = 1, mid = 0;
  for (int64_t k = 0; k < axis; ++k) pre *= xs[0]->dims[k];
  for (int64_t k = axis + 1; k < (int64_t)xs[0]->dims.size(); ++k)
    post *= xs[0]->dims[k];
  for (auto* t : xs) mid += t->dims[axis];
  o->dims = xs[0]->dims;
  o->dims[axis] = mid;
  o->f.resize(pre * mid * post);
  int64_t off = 0;
  for (auto* t : xs) {
    int64_t m = t->dims[axis];
    for (int64_t p = 0; p < pre; ++p)
      memcpy(&o->f[(p * mid + off) * post], &t->f[p * m * post],
             sizeof(float) * m * post);
    off += m;
  }
  return true;
});

static RegK r_split("split", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  if (!x) return false;
  if (x->is_int) {
    c.error = "split: int tensors unsupported in native engine";
    return false;
  }
  int64_t axis = NormAxis(c.AttrI("axis", 0), x->dims.size());
  if (axis < 0 || axis >= (int64_t)x->dims.size()) {
    c.error = "split: bad axis";
    return false;
  }
  int64_t num = c.AttrI("num", 0);
  auto sections = c.AttrInts("sections");
  int out_n = 0;
  for (const auto& s : c.op->outputs())
    if (s.name() == "Out") out_n = s.args_size();
  if (sections.empty()) {
    if (num <= 0) num = out_n;
    if (num <= 0 || x->dims[axis] % num != 0) {
      c.error = "split: bad num";
      return false;
    }
    sections.assign(num, x->dims[axis] / num);
  } else {
    int64_t known = 0, neg = -1;
    for (size_t k = 0; k < sections.size(); ++k)
      if (sections[k] < 0) neg = (int64_t)k; else known += sections[k];
    if (neg >= 0) sections[neg] = x->dims[axis] - known;
  }
  int64_t total = 0;
  for (int64_t s_ : sections) {
    if (s_ <= 0) {
      c.error = "split: non-positive section";
      return false;
    }
    total += s_;
  }
  if (total != x->dims[axis]) {
    c.error = "split: sections do not sum to dims[axis]";
    return false;
  }
  int64_t pre = 1, post = 1, mid = x->dims[axis];
  for (int64_t k = 0; k < axis; ++k) pre *= x->dims[k];
  for (int64_t k = axis + 1; k < (int64_t)x->dims.size(); ++k)
    post *= x->dims[k];
  int64_t off = 0;
  for (size_t i = 0; i < sections.size(); ++i) {
    NTensor* o = c.Out("Out", (int)i);
    if (!o) {
      c.error = "split: missing output";
      return false;
    }
    int64_t m = sections[i];
    o->dims = x->dims;
    o->dims[axis] = m;
    o->f.resize(pre * m * post);
    for (int64_t p = 0; p < pre; ++p)
      memcpy(&o->f[p * m * post], &x->f[(p * mid + off) * post],
             sizeof(float) * m * post);
    off += m;
  }
  return true;
});

// ---- int8 quantized kernels (slim PTQ/QAT artifacts; the reference
// serves these via mkldnn INT8, api/mkldnn_quantizer.cc role). Weights
// arrive int8 (NTensor.q); activations quantize on the fly with the
// calibrated in_scale; accumulation is int32; dequant = in_scale *
// per-channel weight_scale. Matches fluid/lowering.py _quantized_mul.

static inline int8_t QuantAct(float v, float s_in) {
  float r = v / s_in;
  r = r > 127.f ? 127.f : (r < -127.f ? -127.f : r);
  return (int8_t)lrintf(r);
}

static bool QuantizedGemm(ExecCtx& c, bool is_mul) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  if (!x || !y || !o) return false;
  if (!y->is_q) { c.error = "quantized op: weight is not int8"; return false; }
  float s_in = (float)c.AttrF("in_scale", 1.0f / 127.0f);
  auto scales = c.AttrFloats("weight_scales");
  int64_t M = 1, K = 1, N;
  bool ty = false;
  if (is_mul) {
    int64_t xcols = c.AttrI("x_num_col_dims", 1);
    for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
      (k < xcols ? M : K) *= x->dims[k];
    N = y->numel() / y->dims[0];
    o->dims.assign(x->dims.begin(), x->dims.begin() + xcols);
    o->dims.push_back(N);
  } else {
    ty = c.AttrB("transpose_Y", false);
    if (x->dims.size() != 2 || y->dims.size() != 2) {
      c.error = "quantized_matmul: only 2D in native predictor";
      return false;
    }
    M = x->dims[0];
    K = x->dims[1];
    N = ty ? y->dims[0] : y->dims[1];
    o->dims = {M, N};
  }
  std::vector<int8_t> xq(M * K);
  for (int64_t idx = 0; idx < M * K; ++idx)
    xq[idx] = QuantAct(x->f[idx], s_in);
  o->f.assign(M * N, 0.0f);
  o->is_int = false; o->is_q = false;
  for (int64_t m = 0; m < M; ++m)
    for (int64_t n = 0; n < N; ++n) {
      int32_t acc = 0;
      for (int64_t k = 0; k < K; ++k) {
        int8_t wv = ty ? y->q[n * K + k] : y->q[k * N + n];
        acc += (int32_t)xq[m * K + k] * (int32_t)wv;
      }
      float sw = scales.size() == (size_t)N ? (float)scales[n]
                 : (scales.empty() ? 1.f : (float)scales[0]);
      o->f[m * N + n] = (float)acc * s_in * sw;
    }
  return true;
}

static RegK r_qmul("quantized_mul", [](ExecCtx& c) {
  return QuantizedGemm(c, true);
});
static RegK r_qmatmul("quantized_matmul", [](ExecCtx& c) {
  return QuantizedGemm(c, false);
});
static RegK r_qmatmul2("quantized_matmul_v2", [](ExecCtx& c) {
  return QuantizedGemm(c, false);
});

static RegK r_qconv2d("quantized_conv2d", [](ExecCtx& c) {
  NTensor* x = c.In("Input");
  NTensor* w = c.In("Filter");
  NTensor* o = c.Out("Output");
  if (!x || !w || !o) return false;
  if (!w->is_q) { c.error = "quantized_conv2d: weight not int8"; return false; }
  float s_in = (float)c.AttrF("in_scale", 1.0f / 127.0f);
  auto scales = c.AttrFloats("weight_scales");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  auto dil = c.AttrInts("dilations");
  int64_t g = c.AttrI("groups", 1);
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (dil.empty()) dil = {1, 1};
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t OC = w->dims[0], KC = w->dims[1], KH = w->dims[2], KW = w->dims[3];
  int64_t OH = (H + 2 * pads[0] - dil[0] * (KH - 1) - 1) / strides[0] + 1;
  int64_t OW = (W + 2 * pads[1] - dil[1] * (KW - 1) - 1) / strides[1] + 1;
  o->dims = {N, OC, OH, OW};
  o->f.assign(N * OC * OH * OW, 0.0f);
  std::vector<int8_t> xq(x->numel());
  for (int64_t idx = 0; idx < x->numel(); ++idx)
    xq[idx] = QuantAct(x->f[idx], s_in);
  int64_t cpg = C / g, opg = OC / g;
  for (int64_t n = 0; n < N; ++n)
    for (int64_t oc = 0; oc < OC; ++oc) {
      int64_t grp = oc / opg;
      float sw = scales.size() == (size_t)OC ? (float)scales[oc]
                 : (scales.empty() ? 1.f : (float)scales[0]);
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          int32_t acc = 0;
          for (int64_t ic = 0; ic < cpg; ++ic) {
            int64_t cin = grp * cpg + ic;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                if (iw < 0 || iw >= W) continue;
                acc += (int32_t)xq[((n * C + cin) * H + ih) * W + iw] *
                       (int32_t)w->q[((oc * KC + ic) * KH + kh) * KW + kw];
              }
            }
          }
          o->f[((n * OC + oc) * OH + oh) * OW + ow] =
              (float)acc * s_in * sw;
        }
    }
  return true;
});

static RegK r_bn("batch_norm", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* scale = c.In("Scale");
  NTensor* bias = c.In("Bias");
  NTensor* mean = c.In("Mean");
  NTensor* var = c.In("Variance");
  NTensor* o = c.Out("Y");
  if (!o) o = c.Out("Out");
  float eps = (float)c.AttrF("epsilon", 1e-5);
  int64_t N = x->dims[0], C = x->dims[1];
  int64_t HW = x->numel() / (N * C);
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (int64_t n = 0; n < N; ++n)
    for (int64_t ch = 0; ch < C; ++ch) {
      float inv = 1.0f / sqrtf(var->f[ch] + eps);
      float a = scale->f[ch] * inv;
      float b = bias->f[ch] - mean->f[ch] * a;
      const float* xr = &x->f[(n * C + ch) * HW];
      float* orow = &o->f[(n * C + ch) * HW];
      for (int64_t k = 0; k < HW; ++k) orow[k] = a * xr[k] + b;
    }
  return true;
});

static RegK r_transpose("transpose", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  auto perm = c.AttrInts("perm");
  if (perm.empty()) perm = c.AttrInts("axis");
  int nd = (int)x->dims.size();
  o->dims.resize(nd);
  for (int k = 0; k < nd; ++k) o->dims[k] = x->dims[perm[k]];
  std::vector<int64_t> xstr(nd, 1), ostr(nd, 1);
  for (int k = nd - 2; k >= 0; --k)
    xstr[k] = xstr[k + 1] * x->dims[k + 1];
  for (int k = nd - 2; k >= 0; --k)
    ostr[k] = ostr[k + 1] * o->dims[k + 1];
  o->f.resize(x->f.size());
  std::vector<int64_t> idx(nd, 0);
  for (int64_t flat = 0; flat < x->numel(); ++flat) {
    int64_t rem = flat, src = 0;
    for (int k = 0; k < nd; ++k) {
      idx[k] = rem / ostr[k];
      rem %= ostr[k];
      src += idx[k] * xstr[perm[k]];
    }
    o->f[flat] = x->f[src];
  }
  return true;
});

static RegK r_mean("mean", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  double s = 0;
  for (float v : x->f) s += v;
  o->dims = {};
  o->f = {(float)(s / std::max<int64_t>(1, x->numel()))};
  return true;
});

static RegK r_argmax("arg_max", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  int64_t last = x->dims.back();
  int64_t rows = x->numel() / last;
  o->dims.assign(x->dims.begin(), x->dims.end() - 1);
  o->is_int = true;
  o->i.resize(rows);
  for (int64_t r = 0; r < rows; ++r) {
    const float* xr = &x->f[r * last];
    o->i[r] = (int64_t)(std::max_element(xr, xr + last) - xr);
  }
  return true;
});

// ---------------- predictor ----------------

class NativePredictor {
 public:
  std::string error;

  bool Load(const std::string& dir) {
    std::ifstream f(dir + "/__model__", std::ios::binary);
    if (!f) {
      error = "missing __model__ in " + dir;
      return false;
    }
    std::stringstream ss;
    ss << f.rdbuf();
    if (!model_.ParseFromString(ss.str())) {
      error = "bad __model__ proto";
      return false;
    }
    // params: PTC1 combined file
    std::string ppath = dir + "/__params__";
    CombineReader* r = CombineLoad(ppath.c_str());
    if (r) {
      if (!r->complete) {
        error = "truncated __params__";
        delete r;
        return false;
      }
      for (auto& [name, t] : r->entries) {
        NTensor nt;
        nt.dims = t.dims;
        const char* src = t.data.data();
        size_t nb = t.data.size();
        switch (t.dtype) {  // PTT1 codes → f32/i64 working storage
          case 1:  // float32
            nt.f.resize(nb / 4);
            memcpy(nt.f.data(), src, nb);
            break;
          case 2: {  // float64 → f32
            nt.f.resize(nb / 8);
            const double* d = (const double*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) nt.f[k] = (float)d[k];
            break;
          }
          case 3: {  // int32 → i64
            nt.is_int = true;
            nt.i.resize(nb / 4);
            const int32_t* d = (const int32_t*)src;
            for (size_t k = 0; k < nt.i.size(); ++k) nt.i[k] = d[k];
            break;
          }
          case 4:  // int64
            nt.is_int = true;
            nt.i.resize(nb / 8);
            memcpy(nt.i.data(), src, nb);
            break;
          case 5: case 8: {  // bool/uint8 → i64
            nt.is_int = true;
            nt.i.resize(nb);
            for (size_t k = 0; k < nb; ++k) nt.i[k] = (int64_t)(int8_t)src[k];
            break;
          }
          case 9: {  // int8: kept quantized for the quantized_* kernels
            nt.is_q = true;
            nt.q.resize(nb);
            memcpy(nt.q.data(), src, nb);
            break;
          }
          case 6: {  // uint16 carries bf16 bit patterns → f32
            nt.f.resize(nb / 2);
            const uint16_t* d = (const uint16_t*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) {
              uint32_t bits = ((uint32_t)d[k]) << 16;
              memcpy(&nt.f[k], &bits, 4);
            }
            break;
          }
          case 7: {  // float16 → f32
            nt.f.resize(nb / 2);
            const uint16_t* d = (const uint16_t*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) {
              uint16_t h = d[k];
              uint32_t sign = (uint32_t)(h & 0x8000) << 16;
              uint32_t expo = (h >> 10) & 0x1f;
              uint32_t mant = h & 0x3ff;
              uint32_t bits;
              if (expo == 0) {
                if (mant == 0) {
                  bits = sign;
                } else {  // subnormal: normalize
                  int e = -1;
                  do { mant <<= 1; ++e; } while (!(mant & 0x400));
                  bits = sign | ((uint32_t)(127 - 15 - e) << 23)
                       | ((mant & 0x3ff) << 13);
                }
              } else if (expo == 31) {
                bits = sign | 0x7f800000u | (mant << 13);
              } else {
                bits = sign | ((expo - 15 + 127) << 23) | (mant << 13);
              }
              memcpy(&nt.f[k], &bits, 4);
            }
            break;
          }
          default:
            error = "unsupported param dtype code " +
                    std::to_string((int)t.dtype) + " for " + name;
            delete r;
            return false;
        }
        params_[name] = std::move(nt);
      }
      delete r;
    }
    return true;
  }

  void SetInput(const std::string& name, const int64_t* dims, int ndim,
                const float* data) {
    NTensor t;
    t.dims.assign(dims, dims + ndim);
    t.f.assign(data, data + t.numel());
    feeds_[name] = std::move(t);
  }

  bool Run(const std::vector<std::string>& fetch_names) {
    for (const auto& n : model_.feed_names()) {
      if (!feeds_.count(n)) {
        error = "input not set: " + n;
        return false;
      }
    }
    ExecCtx ctx;
    ctx.params = &params_;
    for (auto& [k, v] : feeds_) ctx.vars[k] = v;
    const auto& block = model_.program().blocks(0);
    for (const auto& op : block.ops()) {
      if (op.type() == "feed" || op.type() == "fetch") continue;
      auto it = Registry().find(op.type());
      if (it == Registry().end()) {
        error = "no native kernel for op: " + op.type();
        return false;
      }
      // all declared inputs must exist before the kernel dereferences them
      for (const auto& s : op.inputs())
        for (const auto& arg : s.args())
          if (!ctx.vars.count(arg) && !params_.count(arg)) {
            error = "op " + op.type() + ": input var not set: " + arg;
            return false;
          }
      ctx.op = &op;
      if (!it->second(ctx)) {
        error = "op " + op.type() + " failed: " + ctx.error;
        return false;
      }
    }
    fetches_.clear();
    for (const auto& n : fetch_names) {
      auto it = ctx.vars.find(n);
      if (it != ctx.vars.end()) {
        fetches_.push_back({n, it->second});
        continue;
      }
      auto pit = params_.find(n);
      if (pit == params_.end()) {
        error = "fetch var missing: " + n;
        return false;
      }
      fetches_.push_back({n, pit->second});
    }
    return true;
  }

  const ptframework::InferenceModel& model() const { return model_; }
  std::vector<std::pair<std::string, NTensor>> fetches_;

 private:
  ptframework::InferenceModel model_;
  std::unordered_map<std::string, NTensor> params_;
  std::unordered_map<std::string, NTensor> feeds_;
};

}  // namespace ptcore

// ---------------- C API ----------------

using ptcore::NativePredictor;

extern "C" {

void* pt_pred_create(const char* model_dir) {
  auto* p = new NativePredictor;
  if (!p->Load(model_dir)) {
    // keep object alive so caller can read the error, flag via negative
    // handle convention is awkward in ctypes: expose error through object
  }
  return p;
}
const char* pt_pred_error(void* h) {
  return ((NativePredictor*)h)->error.c_str();
}
int pt_pred_feed_count(void* h) {
  return ((NativePredictor*)h)->model().feed_names_size();
}
const char* pt_pred_feed_name(void* h, int i) {
  return ((NativePredictor*)h)->model().feed_names(i).c_str();
}
int pt_pred_fetch_count(void* h) {
  return ((NativePredictor*)h)->model().fetch_names_size();
}
const char* pt_pred_fetch_name(void* h, int i) {
  return ((NativePredictor*)h)->model().fetch_names(i).c_str();
}
void pt_pred_set_input(void* h, const char* name, const int64_t* dims,
                       int ndim, const float* data) {
  ((NativePredictor*)h)->SetInput(name, dims, ndim, data);
}
int pt_pred_run(void* h) {
  auto* p = (NativePredictor*)h;
  std::vector<std::string> fetches;
  for (const auto& n : p->model().fetch_names()) fetches.push_back(n);
  return p->Run(fetches) ? 0 : -1;
}
int pt_pred_out_ndim(void* h, int i) {
  return (int)((NativePredictor*)h)->fetches_[i].second.dims.size();
}
void pt_pred_out_dims(void* h, int i, int64_t* out) {
  auto& d = ((NativePredictor*)h)->fetches_[i].second.dims;
  memcpy(out, d.data(), d.size() * 8);
}
int pt_pred_out_is_int(void* h, int i) {
  return ((NativePredictor*)h)->fetches_[i].second.is_int ? 1 : 0;
}
void pt_pred_out_copy(void* h, int i, void* out) {
  auto& t = ((NativePredictor*)h)->fetches_[i].second;
  if (t.is_int)
    memcpy(out, t.i.data(), t.i.size() * 8);
  else
    memcpy(out, t.f.data(), t.f.size() * 4);
}
void pt_pred_destroy(void* h) { delete (NativePredictor*)h; }

}  // extern "C"
