// Native inference executor.
//
// Capability parity with the reference's NaiveExecutor
// (framework/naive_executor.h) + AnalysisPredictor C core
// (inference/api/analysis_predictor.cc:288 Run): loads a ProgramDesc proto
// (`__model__`, csrc/proto/ptframework.proto) and a combined params file
// (`__params__`, PTC1), then interprets the op list with a small CPU
// kernel registry — the no-Python deployment path (the XLA path is the
// fast one; this is the standalone C ABI predictor, serving the role of
// paddle/fluid/train's pure-C++ entry and the inference C API).
#include <algorithm>
#include <cmath>
#include <cstring>
#include <fstream>
#include <functional>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

#include "ptframework.pb.h"
#include "saveload.h"

namespace ptcore {

struct NTensor {
  std::vector<int64_t> dims;
  std::vector<float> f;    // float32 storage
  std::vector<int64_t> i;  // int64 storage
  std::vector<int8_t> q;   // int8 storage (slim PTQ/QAT weights)
  std::vector<int64_t> lod;  // level-1 offsets (packed-rows sequences);
                             // empty = dense (lod_tensor.h LoD role)
  bool is_int = false;
  bool is_q = false;

  int64_t numel() const {
    int64_t n = 1;
    for (auto d : dims) n *= d;
    return n;
  }
};

struct ExecCtx {
  std::unordered_map<std::string, NTensor> vars;  // activations (per run)
  const std::unordered_map<std::string, NTensor>* params = nullptr;
  std::unordered_map<std::string, NTensor>* mutable_params = nullptr;
  const ptframework::OpDesc* op = nullptr;
  const ptframework::BlockDesc* block = nullptr;  // for jax_autodiff
  int op_index = -1;
  std::string error;

  // inputs resolve activations first, then read-only params — avoids
  // copying the whole weight map every Run (kernels never write params)
  NTensor* In(const std::string& slot, int idx = 0) {
    for (const auto& s : op->inputs())
      if (s.name() == slot && idx < s.args_size()) {
        const std::string& n = s.args(idx);
        auto it = vars.find(n);
        if (it != vars.end()) return &it->second;
        if (params) {
          auto pit = params->find(n);
          if (pit != params->end())
            return const_cast<NTensor*>(&pit->second);
        }
        error = "input var not set: " + n;
        return nullptr;
      }
    return nullptr;
  }
  NTensor* Out(const std::string& slot, int idx = 0) {
    for (const auto& s : op->outputs())
      if (s.name() == slot && idx < s.args_size())
        return &vars[s.args(idx)];
    return nullptr;
  }
  const ptframework::Attr* FindAttr(const std::string& name) {
    for (const auto& a : op->attrs())
      if (a.name() == name) return &a;
    return nullptr;
  }
  int64_t AttrI(const std::string& n, int64_t dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kI ? a->i() : dflt;
  }
  double AttrF(const std::string& n, double dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kF ? a->f() : dflt;
  }
  bool AttrB(const std::string& n, bool dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kB ? a->b() : dflt;
  }
  std::string AttrS(const std::string& n, const std::string& dflt) {
    auto* a = FindAttr(n);
    return a && a->value_case() == ptframework::Attr::kS ? a->s() : dflt;
  }
  std::vector<int64_t> AttrInts(const std::string& n) {
    auto* a = FindAttr(n);
    std::vector<int64_t> out;
    if (a && a->value_case() == ptframework::Attr::kInts)
      for (auto v : a->ints().val()) out.push_back(v);
    return out;
  }
  std::vector<double> AttrFloats(const std::string& n) {
    auto* a = FindAttr(n);
    std::vector<double> out;
    if (a && a->value_case() == ptframework::Attr::kFloats)
      for (auto v : a->floats().val()) out.push_back(v);
    return out;
  }
};

using Kernel = std::function<bool(ExecCtx&)>;

static std::map<std::string, Kernel>& Registry() {
  static std::map<std::string, Kernel> r;
  return r;
}

struct RegK {
  RegK(const char* name, Kernel k) { Registry()[name] = std::move(k); }
};

// ---------------- kernels ----------------

static bool EwiseUnary(ExecCtx& c, float (*fn)(float)) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = fn(x->f[k]);
  return true;
}

static RegK r_relu("relu", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return v > 0 ? v : 0.0f; });
});
static RegK r_sigmoid("sigmoid", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return 1.0f / (1.0f + expf(-v)); });
});
static RegK r_tanh("tanh", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) { return tanhf(v); });
});

static RegK r_scale("scale", [](ExecCtx& c) {
  float s = (float)c.AttrF("scale", 1.0);
  float b = (float)c.AttrF("bias", 0.0);
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = x->f[k] * s + b;
  return true;
});

static RegK r_dropout("dropout", [](ExecCtx& c) {  // inference: identity
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  *o = *x;
  return true;
});

// reshape/flatten/squeeze/unsqueeze: raw data carryover, dims recomputed.
// shape entry 0 = copy input dim at that index (fluid semantics, matching
// the Python lowering); -1 = infer.
static bool Reshape(ExecCtx& c, std::vector<int64_t> shape) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  int64_t known = 1, infer = -1;
  for (size_t k = 0; k < shape.size(); ++k) {
    if (shape[k] == 0) {
      if (k >= x->dims.size()) {
        c.error = "reshape: 0-dim index out of range";
        return false;
      }
      shape[k] = x->dims[k];
    }
    if (shape[k] == -1) {
      infer = (int64_t)k;
    } else {
      known *= shape[k];
    }
  }
  if (infer >= 0) shape[infer] = x->numel() / (known ? known : 1);
  o->f = x->f;
  o->i = x->i;
  o->is_int = x->is_int;
  o->dims = shape;
  return true;
}

static RegK r_reshape("reshape", [](ExecCtx& c) {
  return Reshape(c, c.AttrInts("shape"));
});
static RegK r_reshape2("reshape2", [](ExecCtx& c) {
  return Reshape(c, c.AttrInts("shape"));
});
static RegK r_flatten("flatten", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  int64_t ax = c.AttrI("axis", 1);
  int64_t d0 = 1, d1 = 1;
  for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
    (k < ax ? d0 : d1) *= x->dims[k];
  return Reshape(c, {d0, d1});
});

static RegK r_mul("mul", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  int64_t xcols = c.AttrI("x_num_col_dims", 1);
  int64_t M = 1, K = 1;
  for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
    (k < xcols ? M : K) *= x->dims[k];
  int64_t K2 = y->dims[0], N = y->numel() / y->dims[0];
  if (K != K2) {
    c.error = "mul: K mismatch";
    return false;
  }
  o->dims.assign(x->dims.begin(), x->dims.begin() + xcols);
  o->dims.push_back(N);
  o->f.assign(M * N, 0.0f);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t k = 0; k < K; ++k) {
      float xv = x->f[m * K + k];
      const float* yr = &y->f[k * N];
      float* orow = &o->f[m * N];
      for (int64_t n = 0; n < N; ++n) orow[n] += xv * yr[n];
    }
  return true;
});

static RegK r_matmul("matmul", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  bool tx = c.AttrB("transpose_X", false), ty = c.AttrB("transpose_Y", false);
  float alpha = (float)c.AttrF("alpha", 1.0);
  if (x->dims.size() != 2 || y->dims.size() != 2) {
    c.error = "matmul: only 2D supported in native predictor";
    return false;
  }
  int64_t M = tx ? x->dims[1] : x->dims[0];
  int64_t K = tx ? x->dims[0] : x->dims[1];
  int64_t N = ty ? y->dims[0] : y->dims[1];
  o->dims = {M, N};
  o->f.assign(M * N, 0.0f);
  for (int64_t m = 0; m < M; ++m)
    for (int64_t k = 0; k < K; ++k) {
      float xv = tx ? x->f[k * M + m] : x->f[m * K + k];
      for (int64_t n = 0; n < N; ++n) {
        float yv = ty ? y->f[n * K + k] : y->f[k * N + n];
        o->f[m * N + n] += alpha * xv * yv;
      }
    }
  return true;
});

static RegK r_eadd("elementwise_add", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  if (x->dims == y->dims) {
    for (size_t k = 0; k < x->f.size(); ++k) o->f[k] = x->f[k] + y->f[k];
    return true;
  }
  // broadcast Y along `axis` (bias pattern): Y dims match
  // x.dims[axis:axis+y.ndim]
  int64_t axis = c.AttrI("axis", -1);
  if (axis < 0) axis = (int64_t)x->dims.size() - (int64_t)y->dims.size();
  int64_t pre = 1, mid = y->numel(), post = 1;
  for (int64_t k = 0; k < axis; ++k) pre *= x->dims[k];
  for (int64_t k = axis + (int64_t)y->dims.size();
       k < (int64_t)x->dims.size(); ++k)
    post *= x->dims[k];
  if (pre * mid * post != x->numel()) {
    c.error = "elementwise_add: bad broadcast";
    return false;
  }
  for (int64_t p = 0; p < pre; ++p)
    for (int64_t m = 0; m < mid; ++m)
      for (int64_t q = 0; q < post; ++q) {
        int64_t idx = (p * mid + m) * post + q;
        o->f[idx] = x->f[idx] + y->f[m];
      }
  return true;
});

static RegK r_softmax("softmax", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  int64_t last = x->dims.back();
  int64_t rows = x->numel() / last;
  for (int64_t r = 0; r < rows; ++r) {
    const float* xr = &x->f[r * last];
    float* orow = &o->f[r * last];
    float mx = xr[0];
    for (int64_t k = 1; k < last; ++k) mx = std::max(mx, xr[k]);
    float sum = 0;
    for (int64_t k = 0; k < last; ++k) {
      orow[k] = expf(xr[k] - mx);
      sum += orow[k];
    }
    for (int64_t k = 0; k < last; ++k) orow[k] /= sum;
  }
  return true;
});

static RegK r_conv2d("conv2d", [](ExecCtx& c) {
  NTensor* x = c.In("Input");
  NTensor* w = c.In("Filter");
  NTensor* o = c.Out("Output");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  auto dil = c.AttrInts("dilations");
  int64_t g = c.AttrI("groups", 1);
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (dil.empty()) dil = {1, 1};
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t OC = w->dims[0], KC = w->dims[1], KH = w->dims[2], KW = w->dims[3];
  int64_t OH = (H + 2 * pads[0] - dil[0] * (KH - 1) - 1) / strides[0] + 1;
  int64_t OW = (W + 2 * pads[1] - dil[1] * (KW - 1) - 1) / strides[1] + 1;
  o->dims = {N, OC, OH, OW};
  o->f.assign(N * OC * OH * OW, 0.0f);
  int64_t cpg = C / g, opg = OC / g;
  for (int64_t n = 0; n < N; ++n)
    for (int64_t oc = 0; oc < OC; ++oc) {
      int64_t grp = oc / opg;
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float acc = 0;
          for (int64_t ic = 0; ic < cpg; ++ic) {
            int64_t cin = grp * cpg + ic;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                if (iw < 0 || iw >= W) continue;
                acc += x->f[((n * C + cin) * H + ih) * W + iw] *
                       w->f[((oc * KC + ic) * KH + kh) * KW + kw];
              }
            }
          }
          o->f[((n * OC + oc) * OH + oh) * OW + ow] = acc;
        }
    }
  return true;
});

static RegK r_pool2d("pool2d", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  auto ksize = c.AttrInts("ksize");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  bool global = c.AttrB("global_pooling", false);
  bool exclusive = c.AttrB("exclusive", true);
  std::string type = c.AttrS("pooling_type", "max");
  bool adaptive = c.AttrB("adaptive", false);
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (global) {
    ksize = {H, W};
    strides = {H, W};
    pads = {0, 0};
  }
  int64_t OH, OW;
  if (adaptive) {
    OH = ksize[0];
    OW = ksize[1];
  } else {
    OH = (H + 2 * pads[0] - ksize[0]) / strides[0] + 1;
    OW = (W + 2 * pads[1] - ksize[1]) / strides[1] + 1;
  }
  o->dims = {N, C, OH, OW};
  o->f.assign(N * C * OH * OW, 0.0f);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t ch = 0; ch < C; ++ch)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          int64_t h0, h1, w0, w1;
          if (adaptive) {
            h0 = oh * H / OH;
            h1 = (oh + 1) * H / OH;
            w0 = ow * W / OW;
            w1 = (ow + 1) * W / OW;
          } else {
            h0 = oh * strides[0] - pads[0];
            h1 = std::min(h0 + ksize[0], H);
            w0 = ow * strides[1] - pads[1];
            w1 = std::min(w0 + ksize[1], W);
            h0 = std::max<int64_t>(h0, 0);
            w0 = std::max<int64_t>(w0, 0);
          }
          float acc = type == "max" ? -3.4e38f : 0.0f;
          int64_t cnt = 0;
          for (int64_t ih = h0; ih < h1; ++ih)
            for (int64_t iw = w0; iw < w1; ++iw) {
              float v = x->f[((n * C + ch) * H + ih) * W + iw];
              if (type == "max")
                acc = std::max(acc, v);
              else
                acc += v;
              cnt++;
            }
          if (type != "max")
            acc /= exclusive ? (float)cnt
                             : (float)(ksize[0] * ksize[1]);
          o->f[((n * C + ch) * OH + oh) * OW + ow] = acc;
        }
  return true;
});

// depthwise_conv2d is conv2d with groups == channels; the grouped conv
// kernel above already handles it (filter [OC, 1, KH, KW])
static RegK r_dwconv("depthwise_conv2d", [](ExecCtx& c) {
  return Registry()["conv2d"](c);
});

static RegK r_relu6("relu6", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) {
    return v < 0 ? 0.0f : (v > 6.0f ? 6.0f : v);
  });
});

// MobileNetV3-family activations (hard_sigmoid/hard_swish)
static RegK r_hsig("hard_sigmoid", [](ExecCtx& c) {
  float slope = (float)c.AttrF("slope", 0.2);
  float offset = (float)c.AttrF("offset", 0.5);
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (size_t k = 0; k < x->f.size(); ++k) {
    float y = x->f[k] * slope + offset;
    o->f[k] = y < 0 ? 0.0f : (y > 1.0f ? 1.0f : y);
  }
  return true;
});
static RegK r_hswish("hard_swish", [](ExecCtx& c) {
  return EwiseUnary(c, [](float v) {
    float t = v + 3.0f;
    t = t < 0 ? 0.0f : (t > 6.0f ? 6.0f : t);
    return v * t / 6.0f;
  });
});

static int64_t NormAxis(int64_t axis, size_t ndim) {
  return axis < 0 ? axis + (int64_t)ndim : axis;
}

// ================= pure-C++ TRAINING (VERDICT r04 missing #5) ========
// The reference trains with no Python (fluid/train/
// test_train_recognize_digits.cc). Our static autodiff collapses the
// backward into ONE `jax_autodiff` op (Loss, Params -> Grads,
// fwd_op_count attr = the forward slice length); the native trainer
// implements that op by reverse-walking the forward slice with a small
// grad-kernel registry, then the program's own sgd ops apply updates
// in the (mutable) param store.

struct GradCtx {
  ExecCtx* c;
  // grad lookup: name@GRAD in vars (created on demand, zero-filled)
  NTensor* Grad(const std::string& name, const NTensor* like) {
    auto& g = c->vars["__grad__" + name];
    if (g.f.empty() && like) {
      g.dims = like->dims;
      g.f.assign((size_t)like->numel(), 0.0f);
    }
    return &g;
  }
  NTensor* GradIfAny(const std::string& name) {
    auto it = c->vars.find("__grad__" + name);
    return it == c->vars.end() ? nullptr : &it->second;
  }
  NTensor* Var(const std::string& name) {
    auto it = c->vars.find(name);
    if (it != c->vars.end()) return &it->second;
    if (c->params) {
      auto pit = c->params->find(name);
      if (pit != c->params->end())
        return const_cast<NTensor*>(&pit->second);
    }
    return nullptr;
  }
};

using GradKernel = std::function<bool(GradCtx&, const ptframework::OpDesc&)>;

static std::map<std::string, GradKernel>& GradRegistry() {
  static std::map<std::string, GradKernel> r;
  return r;
}
struct RegG {
  RegG(const char* name, GradKernel k) {
    GradRegistry()[name] = std::move(k);
  }
};

static const std::string& Arg(const ptframework::OpDesc& op, bool in,
                              const std::string& slot, int idx = 0) {
  static const std::string kEmpty;
  const auto& slots = in ? op.inputs() : op.outputs();
  for (const auto& s : slots)
    if (s.name() == slot && idx < s.args_size()) return s.args(idx);
  return kEmpty;
}

// mul: Out[N,K] = X[N,M] @ Y[M,K] (2-D case). dX = dOut Y^T; dY = X^T dOut
static RegG g_mul("mul", [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* y = g.Var(Arg(op, true, "Y"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !y || !dout) return true;  // no grad flows here
  int64_t M = y->dims[0], K = y->dims[1];
  int64_t N = x->numel() / M;
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  NTensor* dy = g.Grad(Arg(op, true, "Y"), y);
  for (int64_t n = 0; n < N; ++n)
    for (int64_t m = 0; m < M; ++m) {
      float acc = 0.0f;
      const float* dor = &dout->f[(size_t)(n * K)];
      const float* yr = &y->f[(size_t)(m * K)];
      for (int64_t k = 0; k < K; ++k) acc += dor[k] * yr[k];
      dx->f[(size_t)(n * M + m)] += acc;
    }
  for (int64_t m = 0; m < M; ++m)
    for (int64_t k = 0; k < K; ++k) {
      float acc = 0.0f;
      for (int64_t n = 0; n < N; ++n)
        acc += x->f[(size_t)(n * M + m)] * dout->f[(size_t)(n * K + k)];
      dy->f[(size_t)(m * K + k)] += acc;
    }
  return true;
});

// elementwise_add grad: dY reduces over the SAME pre/mid/post
// decomposition the forward broadcast used (axis=1 conv-bias on NCHW
// has post = H*W, so a trailing k%C reduce would scramble it)
static RegG g_eadd("elementwise_add",
                   [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* y = g.Var(Arg(op, true, "Y"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !y || !dout) return true;
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  NTensor* dy = g.Grad(Arg(op, true, "Y"), y);
  for (size_t k = 0; k < dout->f.size(); ++k) dx->f[k] += dout->f[k];
  if (y->numel() == (int64_t)dout->f.size()) {
    for (size_t k = 0; k < dout->f.size(); ++k) dy->f[k] += dout->f[k];
    return true;
  }
  int64_t axis = -1;
  for (const auto& a : op.attrs())
    if (a.name() == "axis" && a.value_case() == ptframework::Attr::kI)
      axis = a.i();
  if (axis < 0) axis = (int64_t)x->dims.size() - (int64_t)y->dims.size();
  int64_t pre = 1, mid = y->numel(), post = 1;
  for (int64_t k = 0; k < axis; ++k) pre *= x->dims[k];
  for (int64_t k = axis + (int64_t)y->dims.size();
       k < (int64_t)x->dims.size(); ++k)
    post *= x->dims[k];
  if (pre * mid * post != (int64_t)dout->f.size()) return false;
  for (int64_t p = 0; p < pre; ++p)
    for (int64_t m = 0; m < mid; ++m) {
      float acc = 0.0f;
      const float* src = &dout->f[(size_t)((p * mid + m) * post)];
      for (int64_t q = 0; q < post; ++q) acc += src[q];
      dy->f[(size_t)m] += acc;
    }
  return true;
});

static RegG g_relu("relu", [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* out = g.Var(Arg(op, false, "Out"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!out || !dout) return true;
  NTensor* dx = g.Grad(Arg(op, true, "X"), out);
  for (size_t k = 0; k < dout->f.size(); ++k)
    dx->f[k] += out->f[k] > 0 ? dout->f[k] : 0.0f;
  return true;
});

static RegG g_sec("square_error_cost",
                  [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* y = g.Var(Arg(op, true, "Y"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !y || !dout) return true;
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  for (size_t k = 0; k < dout->f.size(); ++k)
    dx->f[k] += dout->f[k] * 2.0f * (x->f[k] - y->f[k]);
  return true;
});

static RegG g_mean("mean", [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !dout) return true;
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  float s = dout->f[0] / (float)x->numel();
  for (size_t k = 0; k < dx->f.size(); ++k) dx->f[k] += s;
  return true;
});

// softmax_with_cross_entropy: dLogits = (softmax - onehot) * dLoss_row
static RegG g_swce("softmax_with_cross_entropy",
                   [](GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* sm = g.Var(Arg(op, false, "Softmax"));
  NTensor* lbl = g.Var(Arg(op, true, "Label"));
  NTensor* dloss = g.GradIfAny(Arg(op, false, "Loss"));
  if (!sm || !lbl || !dloss) return true;
  int64_t C = sm->dims.back();
  int64_t N = sm->numel() / C;
  if (!lbl->is_int || (int64_t)lbl->i.size() < N) return false;
  NTensor* dx = g.Grad(Arg(op, true, "Logits"), sm);
  for (int64_t n = 0; n < N; ++n) {
    float dl = dloss->f[(size_t)n];
    int64_t t = lbl->i[(size_t)n];
    if (t < 0 || t >= C) return false;
    for (int64_t cc = 0; cc < C; ++cc)
      dx->f[(size_t)(n * C + cc)] +=
          dl * (sm->f[(size_t)(n * C + cc)] - (cc == t ? 1.0f : 0.0f));
  }
  return true;
});

static bool ReshapeGrad(GradCtx& g, const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !dout) return true;
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  for (size_t k = 0; k < dout->f.size(); ++k) dx->f[k] += dout->f[k];
  return true;
}
static RegG g_reshape("reshape2", ReshapeGrad);
static RegG g_reshape1("reshape", ReshapeGrad);
static RegG g_flatten("flatten", ReshapeGrad);

// conv2d NCHW direct-loop backward (LeNet-scale shapes)
static RegG g_conv("conv2d", [](GradCtx& g,
                                const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "Input"));
  NTensor* w = g.Var(Arg(op, true, "Filter"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Output"));
  if (!x || !w || !dout) return true;
  auto attr_ints = [&](const char* nm) {
    std::vector<int64_t> out;
    for (const auto& a : op.attrs())
      if (a.name() == nm && a.value_case() == ptframework::Attr::kInts)
        for (auto v : a.ints().val()) out.push_back(v);
    return out;
  };
  auto strides = attr_ints("strides");
  auto pads = attr_ints("paddings");
  int64_t sh = strides.empty() ? 1 : strides[0];
  int64_t sw = strides.size() > 1 ? strides[1] : sh;
  int64_t ph = pads.empty() ? 0 : pads[0];
  int64_t pw = pads.size() > 1 ? pads[1] : ph;
  int64_t B = x->dims[0], CI = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t CO = w->dims[0], KH = w->dims[2], KW = w->dims[3];
  int64_t OH = dout->dims[2], OW = dout->dims[3];
  NTensor* dx = g.Grad(Arg(op, true, "Input"), x);
  NTensor* dw = g.Grad(Arg(op, true, "Filter"), w);
  for (int64_t b = 0; b < B; ++b)
    for (int64_t co = 0; co < CO; ++co)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float dv = dout->f[(size_t)(((b * CO + co) * OH + oh) * OW
                                      + ow)];
          if (dv == 0.0f) continue;
          for (int64_t ci = 0; ci < CI; ++ci)
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * sh - ph + kh;
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * sw - pw + kw;
                if (iw < 0 || iw >= W) continue;
                size_t xi = (size_t)(((b * CI + ci) * H + ih) * W + iw);
                size_t wi = (size_t)(((co * CI + ci) * KH + kh) * KW
                                     + kw);
                dx->f[xi] += dv * w->f[wi];
                dw->f[wi] += dv * x->f[xi];
              }
            }
        }
  return true;
});

// pool2d max backward: route grads to the argmax position
static RegG g_pool("pool2d", [](GradCtx& g,
                                const ptframework::OpDesc& op) {
  NTensor* x = g.Var(Arg(op, true, "X"));
  NTensor* out = g.Var(Arg(op, false, "Out"));
  NTensor* dout = g.GradIfAny(Arg(op, false, "Out"));
  if (!x || !out || !dout) return true;
  std::string ptype = "max";
  std::vector<int64_t> ks, strides, pads;
  bool global = false;
  for (const auto& a : op.attrs()) {
    if (a.name() == "pooling_type"
        && a.value_case() == ptframework::Attr::kS) ptype = a.s();
    if (a.value_case() == ptframework::Attr::kInts) {
      std::vector<int64_t> v;
      for (auto vv : a.ints().val()) v.push_back(vv);
      if (a.name() == "ksize") ks = v;
      else if (a.name() == "strides") strides = v;
      else if (a.name() == "paddings") pads = v;
    }
    if (a.name() == "global_pooling"
        && a.value_case() == ptframework::Attr::kB) global = a.b();
  }
  int64_t B = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t OH = dout->dims[2], OW = dout->dims[3];
  int64_t kh = global ? H : (ks.empty() ? 2 : ks[0]);
  int64_t kw = global ? W : (ks.size() > 1 ? ks[1] : kh);
  int64_t sh = global ? 1 : (strides.empty() ? kh : strides[0]);
  int64_t sw = global ? 1 : (strides.size() > 1 ? strides[1] : sh);
  int64_t ph = global ? 0 : (pads.empty() ? 0 : pads[0]);
  int64_t pw = global ? 0 : (pads.size() > 1 ? pads[1] : ph);
  NTensor* dx = g.Grad(Arg(op, true, "X"), x);
  for (int64_t b = 0; b < B; ++b)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          float dv = dout->f[(size_t)(((b * C + c) * OH + oh) * OW + ow)];
          if (dv == 0.0f) continue;
          int64_t h0 = oh * sh - ph, w0 = ow * sw - pw;
          if (ptype == "avg") {
            int64_t cnt = 0;
            for (int64_t i = 0; i < kh; ++i)
              for (int64_t j = 0; j < kw; ++j) {
                int64_t ih = h0 + i, iw = w0 + j;
                if (ih >= 0 && ih < H && iw >= 0 && iw < W) ++cnt;
              }
            float share = dv / (float)(cnt ? cnt : 1);
            for (int64_t i = 0; i < kh; ++i)
              for (int64_t j = 0; j < kw; ++j) {
                int64_t ih = h0 + i, iw = w0 + j;
                if (ih >= 0 && ih < H && iw >= 0 && iw < W)
                  dx->f[(size_t)(((b * C + c) * H + ih) * W + iw)] +=
                      share;
              }
          } else {
            float best = -1e30f;
            size_t bi = 0;
            for (int64_t i = 0; i < kh; ++i)
              for (int64_t j = 0; j < kw; ++j) {
                int64_t ih = h0 + i, iw = w0 + j;
                if (ih < 0 || ih >= H || iw < 0 || iw >= W) continue;
                size_t xi = (size_t)(((b * C + c) * H + ih) * W + iw);
                if (x->f[xi] > best) { best = x->f[xi]; bi = xi; }
              }
            dx->f[bi] += dv;
          }
        }
  return true;
});

// the fused-backward op itself: reverse-walk the forward slice
static RegK r_autodiff("jax_autodiff", [](ExecCtx& c) {
  if (!c.block || c.op_index < 0) {
    c.error = "jax_autodiff: no block context";
    return false;
  }
  int64_t fwd_n = c.AttrI("fwd_op_count", c.op_index);
  if (fwd_n > c.op_index) fwd_n = c.op_index;
  const std::string loss = c.AttrS("loss_name", "");
  GradCtx g{&c};
  NTensor* lt = g.Var(loss);
  if (!lt) { c.error = "jax_autodiff: loss var missing"; return false; }
  NTensor* dl = g.Grad(loss, lt);
  for (auto& v : dl->f) v = 1.0f;
  for (int k = (int)fwd_n - 1; k >= 0; --k) {
    const auto& op = c.block->ops(k);
    if (op.type() == "feed" || op.type() == "fetch") continue;
    auto it = GradRegistry().find(op.type());
    if (it == GradRegistry().end()) {
      c.error = "no native grad kernel for op: " + op.type();
      return false;
    }
    if (!it->second(g, op)) {
      c.error = "grad of " + op.type() + " failed";
      return false;
    }
  }
  // publish the declared Grads outputs from the internal grad map
  for (const auto& s : c.op->outputs()) {
    if (s.name() != "Grads") continue;
    for (int k = 0; k < s.args_size(); ++k) {
      std::string gname = s.args(k);  // param@GRAD
      std::string pname = gname.substr(0, gname.rfind("@GRAD"));
      NTensor* gv = g.GradIfAny(pname);
      if (!gv) { c.error = "missing grad for " + pname; return false; }
      c.vars[gname] = *gv;
    }
  }
  return true;
});

static RegK r_swce_fwd("softmax_with_cross_entropy", [](ExecCtx& c) {
  NTensor* x = c.In("Logits");
  NTensor* lbl = c.In("Label");
  NTensor* sm = c.Out("Softmax");
  NTensor* loss = c.Out("Loss");
  if (!x || !lbl || !sm || !loss) {
    c.error = "softmax_with_cross_entropy: missing io";
    return false;
  }
  int64_t C = x->dims.back();
  int64_t N = x->numel() / C;
  if (!lbl->is_int || (int64_t)lbl->i.size() < N) {
    c.error = "softmax_with_cross_entropy: Label must be int64 [N,1]";
    return false;
  }
  sm->dims = x->dims;
  sm->f.resize(x->f.size());
  sm->is_int = false;
  loss->dims = {N, 1};
  loss->f.resize((size_t)N);
  loss->is_int = false;
  for (int64_t n = 0; n < N; ++n) {
    const float* xr = &x->f[(size_t)(n * C)];
    float mx = xr[0];
    for (int64_t k = 1; k < C; ++k) mx = std::max(mx, xr[k]);
    float denom = 0.0f;
    for (int64_t k = 0; k < C; ++k) {
      sm->f[(size_t)(n * C + k)] = std::exp(xr[k] - mx);
      denom += sm->f[(size_t)(n * C + k)];
    }
    for (int64_t k = 0; k < C; ++k) sm->f[(size_t)(n * C + k)] /= denom;
    int64_t t = lbl->i[(size_t)n];
    if (t < 0 || t >= C) {
      c.error = "softmax_with_cross_entropy: label out of range";
      return false;
    }
    loss->f[(size_t)n] =
        -std::log(std::max(sm->f[(size_t)(n * C + t)], 1e-30f));
  }
  return true;
});

static RegK r_sec_fwd("square_error_cost", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  if (!x || !y || !o) {
    c.error = "square_error_cost: missing io";
    return false;
  }
  o->dims = x->dims;
  o->f.resize(x->f.size());
  o->is_int = false;
  for (size_t k = 0; k < x->f.size(); ++k) {
    float d = x->f[k] - y->f[k];
    o->f[k] = d * d;
  }
  return true;
});

static RegK r_sgd("sgd", [](ExecCtx& c) {
  NTensor* grad = c.In("Grad");
  NTensor* lr = c.In("LearningRate");
  if (!grad || !lr) { c.error = "sgd: missing grad/lr"; return false; }
  const std::string& pname = Arg(*c.op, true, "Param");
  NTensor* p = nullptr;
  if (c.mutable_params) {
    auto it = c.mutable_params->find(pname);
    if (it != c.mutable_params->end()) p = &it->second;
  }
  if (!p) {
    auto it = c.vars.find(pname);
    if (it != c.vars.end()) p = &it->second;
  }
  if (!p) { c.error = "sgd: param not found: " + pname; return false; }
  if (p->f.size() != grad->f.size()) {
    // a silent min(size) loop would update only a prefix of the
    // parameter on a shape mismatch (ADVICE r05)
    c.error = "sgd: Param/Grad size mismatch for " + pname + ": " +
              std::to_string(p->f.size()) + " vs " +
              std::to_string(grad->f.size());
    return false;
  }
  float lrv = lr->f.empty() ? 0.01f : lr->f[0];
  for (size_t k = 0; k < p->f.size(); ++k)
    p->f[k] -= lrv * grad->f[k];
  return true;
});

// ---- industrial CTR/NLP serving family (VERDICT r04 missing #4):
// lookup_table / sequence_pool / attention_lstm so saved sparse-id
// artifacts serve on the native engine, not only via XLA.
// Reference: operators/lookup_table_op.cc, sequence_ops/
// sequence_pool_op.cc, attention_lstm_op.cc. ----

static bool LookupTable(ExecCtx& c) {
  NTensor* ids = c.In("Ids");
  NTensor* w = c.In("W");
  NTensor* o = c.Out("Out");
  if (!ids || !w || !o) { c.error = "lookup_table: missing io"; return false; }
  if (!ids->is_int) { c.error = "lookup_table: Ids must be int64"; return false; }
  if (w->dims.size() != 2) { c.error = "lookup_table: W must be [V, D]"; return false; }
  int64_t V = w->dims[0], D = w->dims[1];
  int64_t pad = c.AttrI("padding_idx", -1);
  int64_t n = (int64_t)ids->i.size();
  // out shape: ids dims with a trailing 1 replaced by D ([N,1]->[N,D]);
  // otherwise append D ([B,T]->[B,T,D], lookup_table_v2 form)
  o->dims = ids->dims;
  if (!o->dims.empty() && o->dims.back() == 1) o->dims.back() = D;
  else o->dims.push_back(D);
  o->f.assign((size_t)(n * D), 0.0f);
  o->is_int = false;
  for (int64_t k = 0; k < n; ++k) {
    int64_t id = ids->i[(size_t)k];
    if (id == pad) continue;  // padding rows stay zero
    if (id < 0 || id >= V) {
      c.error = "lookup_table: id out of range";
      return false;
    }
    std::memcpy(&o->f[(size_t)(k * D)], &w->f[(size_t)(id * D)],
                (size_t)D * 4);
  }
  o->lod = ids->lod;  // rows keep the id stream's sequence structure
  return true;
}
static RegK r_lut("lookup_table", LookupTable);
static RegK r_lut2("lookup_table_v2", LookupTable);

static RegK r_seqpool("sequence_pool", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  if (!x || !o) { c.error = "sequence_pool: missing io"; return false; }
  int64_t N = x->dims.empty() ? 0 : x->dims[0];
  int64_t D = x->numel() / (N ? N : 1);
  std::vector<int64_t> off = x->lod;
  if (off.empty()) {  // dense fallback: every row its own sequence of 1
    off.resize((size_t)N + 1);
    for (int64_t k = 0; k <= N; ++k) off[(size_t)k] = k;
  }
  int64_t S = (int64_t)off.size() - 1;
  std::string pt = c.AttrS("pooltype", "AVERAGE");
  float pad_value = (float)c.AttrF("pad_value", 0.0);
  o->dims = {S, D};
  o->f.assign((size_t)(S * D), 0.0f);
  o->is_int = false;
  o->lod.clear();
  for (int64_t s = 0; s < S; ++s) {
    int64_t st = off[(size_t)s], en = off[(size_t)s + 1];
    float* dst = &o->f[(size_t)(s * D)];
    if (st >= en) {  // empty sequence pools to pad_value
      for (int64_t d = 0; d < D; ++d) dst[d] = pad_value;
      continue;
    }
    if (pt == "FIRST") {
      std::memcpy(dst, &x->f[(size_t)(st * D)], (size_t)D * 4);
    } else if (pt == "LAST") {
      std::memcpy(dst, &x->f[(size_t)((en - 1) * D)], (size_t)D * 4);
    } else if (pt == "MAX") {
      for (int64_t d = 0; d < D; ++d) dst[d] = x->f[(size_t)(st * D + d)];
      for (int64_t r = st + 1; r < en; ++r)
        for (int64_t d = 0; d < D; ++d)
          dst[d] = std::max(dst[d], x->f[(size_t)(r * D + d)]);
    } else {  // SUM / AVERAGE / SQRT share the accumulate
      for (int64_t r = st; r < en; ++r)
        for (int64_t d = 0; d < D; ++d) dst[d] += x->f[(size_t)(r * D + d)];
      if (pt == "AVERAGE") {
        float inv = 1.0f / (float)(en - st);
        for (int64_t d = 0; d < D; ++d) dst[d] *= inv;
      } else if (pt == "SQRT") {
        float inv = 1.0f / std::sqrt((float)(en - st));
        for (int64_t d = 0; d < D; ++d) dst[d] *= inv;
      } else if (pt != "SUM") {
        c.error = "sequence_pool: pooltype " + pt + " unsupported";
        return false;
      }
    }
  }
  return true;
});

static float ActGate(const std::string& a, float v) {
  if (a == "sigmoid") return 1.0f / (1.0f + std::exp(-v));
  if (a == "tanh") return std::tanh(v);
  if (a == "relu") return v > 0 ? v : 0.0f;
  return v;  // identity
}

static RegK r_attn_lstm("attention_lstm", [](ExecCtx& c) {
  // attention_lstm_op.cc semantics, matching the XLA lowering
  // (fluid/lowering_batch6.py): per step, scores over ALL the
  // sequence's tokens from token-fc + prev-cell-fc -> relu -> softmax;
  // the attended sum feeds one LSTM step; gate order [f, i, o, cand];
  // LSTMWeight rows [0:D] recur (h), [D:D+M] input (x).
  NTensor* x = c.In("X");
  NTensor* aw = c.In("AttentionWeight");
  NTensor* ab = c.In("AttentionBias");
  NTensor* lw = c.In("LSTMWeight");
  NTensor* lb = c.In("LSTMBias");
  NTensor* oh = c.Out("Hidden");
  NTensor* oc = c.Out("Cell");
  NTensor* oa = c.Out("AttentionedX");
  if (!x || !aw || !lw || !lb || !oh || !oc) {
    c.error = "attention_lstm: missing io";
    return false;
  }
  if (x->lod.empty()) {
    c.error = "attention_lstm: X needs sequence lod";
    return false;
  }
  int64_t N = x->dims[0], M = x->dims[1];
  int64_t D4 = lw->dims[1], D = D4 / 4;
  if (lw->dims[0] != D + M) {
    c.error = "attention_lstm: LSTMWeight must be [D+M, 4D]";
    return false;
  }
  std::string ag = c.AttrS("gate_activation", "sigmoid");
  std::string ac = c.AttrS("cell_activation", "tanh");
  std::string ad = c.AttrS("candidate_activation", "tanh");
  const float* awm = aw->f.data();            // [M] token fc
  const float* awd = aw->f.data() + M;        // [D] cell fc
  float abv = (ab && !ab->f.empty()) ? ab->f[0] : 0.0f;
  const float* wh = lw->f.data();             // rows [0:D]  -> [D,4D]
  const float* wx = lw->f.data() + (size_t)(D * D4);  // rows [D:D+M]
  const float* bias = lb->f.data();           // [4D]
  oh->dims = {N, D}; oh->f.assign((size_t)(N * D), 0.0f);
  oc->dims = {N, D}; oc->f.assign((size_t)(N * D), 0.0f);
  oh->lod = x->lod; oc->lod = x->lod;
  oh->is_int = oc->is_int = false;
  if (oa) {
    oa->dims = {N, 1}; oa->f.assign((size_t)N, 0.0f);
    oa->lod = x->lod; oa->is_int = false;
  }
  std::vector<float> atted, e, a, lstm_x((size_t)M), gates((size_t)D4);
  std::vector<float> h((size_t)D), cc((size_t)D);
  for (size_t s = 0; s + 1 < x->lod.size(); ++s) {
    int64_t st = x->lod[s], en = x->lod[s + 1], L = en - st;
    if (L <= 0) continue;
    atted.assign((size_t)L, 0.0f);
    for (int64_t j = 0; j < L; ++j) {
      const float* xr = &x->f[(size_t)((st + j) * M)];
      float v = abv;
      for (int64_t m = 0; m < M; ++m) v += xr[m] * awm[m];
      atted[(size_t)j] = v;
      if (oa) oa->f[(size_t)(st + j)] = v;
    }
    std::fill(h.begin(), h.end(), 0.0f);
    std::fill(cc.begin(), cc.end(), 0.0f);
    e.assign((size_t)L, 0.0f);
    a.assign((size_t)L, 0.0f);
    for (int64_t t = 0; t < L; ++t) {
      float cdot = 0.0f;
      for (int64_t d = 0; d < D; ++d) cdot += cc[(size_t)d] * awd[d];
      float mx = -1e30f;
      for (int64_t j = 0; j < L; ++j) {
        float v = atted[(size_t)j] + cdot;
        e[(size_t)j] = v > 0 ? v : 0.0f;               // relu
        mx = std::max(mx, e[(size_t)j]);
      }
      float denom = 0.0f;
      for (int64_t j = 0; j < L; ++j) {
        a[(size_t)j] = std::exp(e[(size_t)j] - mx);
        denom += a[(size_t)j];
      }
      std::fill(lstm_x.begin(), lstm_x.end(), 0.0f);
      for (int64_t j = 0; j < L; ++j) {
        float wgt = a[(size_t)j] / denom;
        const float* xr = &x->f[(size_t)((st + j) * M)];
        for (int64_t m = 0; m < M; ++m) lstm_x[(size_t)m] += wgt * xr[m];
      }
      for (int64_t g = 0; g < D4; ++g) gates[(size_t)g] = bias[g];
      for (int64_t m = 0; m < M; ++m) {
        float xv = lstm_x[(size_t)m];
        if (xv == 0.0f) continue;
        const float* wr = &wx[(size_t)(m * D4)];
        for (int64_t g = 0; g < D4; ++g) gates[(size_t)g] += xv * wr[g];
      }
      for (int64_t d = 0; d < D; ++d) {
        float hv = h[(size_t)d];
        if (hv == 0.0f) continue;
        const float* wr = &wh[(size_t)(d * D4)];
        for (int64_t g = 0; g < D4; ++g) gates[(size_t)g] += hv * wr[g];
      }
      for (int64_t d = 0; d < D; ++d) {
        float f = ActGate(ag, gates[(size_t)d]);
        float i = ActGate(ag, gates[(size_t)(D + d)]);
        float o = ActGate(ag, gates[(size_t)(2 * D + d)]);
        float cand = ActGate(ad, gates[(size_t)(3 * D + d)]);
        cc[(size_t)d] = f * cc[(size_t)d] + i * cand;
        h[(size_t)d] = ActGate(ac, cc[(size_t)d]) * o;
      }
      std::memcpy(&oh->f[(size_t)((st + t) * D)], h.data(), (size_t)D * 4);
      std::memcpy(&oc->f[(size_t)((st + t) * D)], cc.data(), (size_t)D * 4);
    }
  }
  return true;
});

static RegK r_concat("concat", [](ExecCtx& c) {
  // gather the X arg list
  std::vector<NTensor*> xs;
  for (const auto& s : c.op->inputs())
    if (s.name() == "X")
      for (int k = 0; k < s.args_size(); ++k) {
        NTensor* t = c.In("X", k);
        if (!t) return false;
        xs.push_back(t);
      }
  if (xs.empty()) {
    c.error = "concat: no inputs";
    return false;
  }
  NTensor* o = c.Out("Out");
  int64_t axis = NormAxis(c.AttrI("axis", 0), xs[0]->dims.size());
  if (axis < 0 || axis >= (int64_t)xs[0]->dims.size()) {
    c.error = "concat: bad axis";
    return false;
  }
  // every input must share rank and non-axis dims (and float storage:
  // the int64 path isn't wired here)
  for (auto* t : xs) {
    if (t->is_int) {
      c.error = "concat: int tensors unsupported in native engine";
      return false;
    }
    if (t->dims.size() != xs[0]->dims.size()) {
      c.error = "concat: rank mismatch";
      return false;
    }
    for (size_t k = 0; k < t->dims.size(); ++k)
      if ((int64_t)k != axis && t->dims[k] != xs[0]->dims[k]) {
        c.error = "concat: non-axis dim mismatch";
        return false;
      }
  }
  int64_t pre = 1, post = 1, mid = 0;
  for (int64_t k = 0; k < axis; ++k) pre *= xs[0]->dims[k];
  for (int64_t k = axis + 1; k < (int64_t)xs[0]->dims.size(); ++k)
    post *= xs[0]->dims[k];
  for (auto* t : xs) mid += t->dims[axis];
  o->dims = xs[0]->dims;
  o->dims[axis] = mid;
  o->f.resize(pre * mid * post);
  int64_t off = 0;
  for (auto* t : xs) {
    int64_t m = t->dims[axis];
    for (int64_t p = 0; p < pre; ++p)
      memcpy(&o->f[(p * mid + off) * post], &t->f[p * m * post],
             sizeof(float) * m * post);
    off += m;
  }
  return true;
});

static RegK r_split("split", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  if (!x) return false;
  if (x->is_int) {
    c.error = "split: int tensors unsupported in native engine";
    return false;
  }
  int64_t axis = NormAxis(c.AttrI("axis", 0), x->dims.size());
  if (axis < 0 || axis >= (int64_t)x->dims.size()) {
    c.error = "split: bad axis";
    return false;
  }
  int64_t num = c.AttrI("num", 0);
  auto sections = c.AttrInts("sections");
  int out_n = 0;
  for (const auto& s : c.op->outputs())
    if (s.name() == "Out") out_n = s.args_size();
  if (sections.empty()) {
    if (num <= 0) num = out_n;
    if (num <= 0 || x->dims[axis] % num != 0) {
      c.error = "split: bad num";
      return false;
    }
    sections.assign(num, x->dims[axis] / num);
  } else {
    int64_t known = 0, neg = -1;
    for (size_t k = 0; k < sections.size(); ++k)
      if (sections[k] < 0) neg = (int64_t)k; else known += sections[k];
    if (neg >= 0) sections[neg] = x->dims[axis] - known;
  }
  int64_t total = 0;
  for (int64_t s_ : sections) {
    if (s_ <= 0) {
      c.error = "split: non-positive section";
      return false;
    }
    total += s_;
  }
  if (total != x->dims[axis]) {
    c.error = "split: sections do not sum to dims[axis]";
    return false;
  }
  int64_t pre = 1, post = 1, mid = x->dims[axis];
  for (int64_t k = 0; k < axis; ++k) pre *= x->dims[k];
  for (int64_t k = axis + 1; k < (int64_t)x->dims.size(); ++k)
    post *= x->dims[k];
  int64_t off = 0;
  for (size_t i = 0; i < sections.size(); ++i) {
    NTensor* o = c.Out("Out", (int)i);
    if (!o) {
      c.error = "split: missing output";
      return false;
    }
    int64_t m = sections[i];
    o->dims = x->dims;
    o->dims[axis] = m;
    o->f.resize(pre * m * post);
    for (int64_t p = 0; p < pre; ++p)
      memcpy(&o->f[p * m * post], &x->f[(p * mid + off) * post],
             sizeof(float) * m * post);
    off += m;
  }
  return true;
});

// ---- int8 quantized kernels (slim PTQ/QAT artifacts; the reference
// serves these via mkldnn INT8, api/mkldnn_quantizer.cc role). Weights
// arrive int8 (NTensor.q); activations quantize on the fly with the
// calibrated in_scale; accumulation is int32; dequant = in_scale *
// per-channel weight_scale. Matches fluid/lowering.py _quantized_mul.

static inline int8_t QuantAct(float v, float s_in) {
  float r = v / s_in;
  r = r > 127.f ? 127.f : (r < -127.f ? -127.f : r);
  return (int8_t)lrintf(r);
}

static bool QuantizedGemm(ExecCtx& c, bool is_mul) {
  NTensor* x = c.In("X");
  NTensor* y = c.In("Y");
  NTensor* o = c.Out("Out");
  if (!x || !y || !o) return false;
  if (!y->is_q) { c.error = "quantized op: weight is not int8"; return false; }
  float s_in = (float)c.AttrF("in_scale", 1.0f / 127.0f);
  auto scales = c.AttrFloats("weight_scales");
  int64_t M = 1, K = 1, N;
  bool ty = false;
  if (is_mul) {
    int64_t xcols = c.AttrI("x_num_col_dims", 1);
    for (int64_t k = 0; k < (int64_t)x->dims.size(); ++k)
      (k < xcols ? M : K) *= x->dims[k];
    N = y->numel() / y->dims[0];
    o->dims.assign(x->dims.begin(), x->dims.begin() + xcols);
    o->dims.push_back(N);
  } else {
    ty = c.AttrB("transpose_Y", false);
    if (x->dims.size() != 2 || y->dims.size() != 2) {
      c.error = "quantized_matmul: only 2D in native predictor";
      return false;
    }
    M = x->dims[0];
    K = x->dims[1];
    N = ty ? y->dims[0] : y->dims[1];
    o->dims = {M, N};
  }
  std::vector<int8_t> xq(M * K);
  for (int64_t idx = 0; idx < M * K; ++idx)
    xq[idx] = QuantAct(x->f[idx], s_in);
  o->f.assign(M * N, 0.0f);
  o->is_int = false; o->is_q = false;
  for (int64_t m = 0; m < M; ++m)
    for (int64_t n = 0; n < N; ++n) {
      int32_t acc = 0;
      for (int64_t k = 0; k < K; ++k) {
        int8_t wv = ty ? y->q[n * K + k] : y->q[k * N + n];
        acc += (int32_t)xq[m * K + k] * (int32_t)wv;
      }
      float sw = scales.size() == (size_t)N ? (float)scales[n]
                 : (scales.empty() ? 1.f : (float)scales[0]);
      o->f[m * N + n] = (float)acc * s_in * sw;
    }
  return true;
}

static RegK r_qmul("quantized_mul", [](ExecCtx& c) {
  return QuantizedGemm(c, true);
});
static RegK r_qmatmul("quantized_matmul", [](ExecCtx& c) {
  return QuantizedGemm(c, false);
});
static RegK r_qmatmul2("quantized_matmul_v2", [](ExecCtx& c) {
  return QuantizedGemm(c, false);
});

static RegK r_qconv2d("quantized_conv2d", [](ExecCtx& c) {
  NTensor* x = c.In("Input");
  NTensor* w = c.In("Filter");
  NTensor* o = c.Out("Output");
  if (!x || !w || !o) return false;
  if (!w->is_q) { c.error = "quantized_conv2d: weight not int8"; return false; }
  float s_in = (float)c.AttrF("in_scale", 1.0f / 127.0f);
  auto scales = c.AttrFloats("weight_scales");
  auto strides = c.AttrInts("strides");
  auto pads = c.AttrInts("paddings");
  auto dil = c.AttrInts("dilations");
  int64_t g = c.AttrI("groups", 1);
  if (strides.empty()) strides = {1, 1};
  if (pads.empty()) pads = {0, 0};
  if (dil.empty()) dil = {1, 1};
  int64_t N = x->dims[0], C = x->dims[1], H = x->dims[2], W = x->dims[3];
  int64_t OC = w->dims[0], KC = w->dims[1], KH = w->dims[2], KW = w->dims[3];
  int64_t OH = (H + 2 * pads[0] - dil[0] * (KH - 1) - 1) / strides[0] + 1;
  int64_t OW = (W + 2 * pads[1] - dil[1] * (KW - 1) - 1) / strides[1] + 1;
  o->dims = {N, OC, OH, OW};
  o->f.assign(N * OC * OH * OW, 0.0f);
  std::vector<int8_t> xq(x->numel());
  for (int64_t idx = 0; idx < x->numel(); ++idx)
    xq[idx] = QuantAct(x->f[idx], s_in);
  int64_t cpg = C / g, opg = OC / g;
  for (int64_t n = 0; n < N; ++n)
    for (int64_t oc = 0; oc < OC; ++oc) {
      int64_t grp = oc / opg;
      float sw = scales.size() == (size_t)OC ? (float)scales[oc]
                 : (scales.empty() ? 1.f : (float)scales[0]);
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          int32_t acc = 0;
          for (int64_t ic = 0; ic < cpg; ++ic) {
            int64_t cin = grp * cpg + ic;
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * strides[0] - pads[0] + kh * dil[0];
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * strides[1] - pads[1] + kw * dil[1];
                if (iw < 0 || iw >= W) continue;
                acc += (int32_t)xq[((n * C + cin) * H + ih) * W + iw] *
                       (int32_t)w->q[((oc * KC + ic) * KH + kh) * KW + kw];
              }
            }
          }
          o->f[((n * OC + oc) * OH + oh) * OW + ow] =
              (float)acc * s_in * sw;
        }
    }
  return true;
});

static RegK r_bn("batch_norm", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* scale = c.In("Scale");
  NTensor* bias = c.In("Bias");
  NTensor* mean = c.In("Mean");
  NTensor* var = c.In("Variance");
  NTensor* o = c.Out("Y");
  if (!o) o = c.Out("Out");
  float eps = (float)c.AttrF("epsilon", 1e-5);
  int64_t N = x->dims[0], C = x->dims[1];
  int64_t HW = x->numel() / (N * C);
  o->dims = x->dims;
  o->f.resize(x->f.size());
  for (int64_t n = 0; n < N; ++n)
    for (int64_t ch = 0; ch < C; ++ch) {
      float inv = 1.0f / sqrtf(var->f[ch] + eps);
      float a = scale->f[ch] * inv;
      float b = bias->f[ch] - mean->f[ch] * a;
      const float* xr = &x->f[(n * C + ch) * HW];
      float* orow = &o->f[(n * C + ch) * HW];
      for (int64_t k = 0; k < HW; ++k) orow[k] = a * xr[k] + b;
    }
  return true;
});

static RegK r_transpose("transpose", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  auto perm = c.AttrInts("perm");
  if (perm.empty()) perm = c.AttrInts("axis");
  int nd = (int)x->dims.size();
  o->dims.resize(nd);
  for (int k = 0; k < nd; ++k) o->dims[k] = x->dims[perm[k]];
  std::vector<int64_t> xstr(nd, 1), ostr(nd, 1);
  for (int k = nd - 2; k >= 0; --k)
    xstr[k] = xstr[k + 1] * x->dims[k + 1];
  for (int k = nd - 2; k >= 0; --k)
    ostr[k] = ostr[k + 1] * o->dims[k + 1];
  o->f.resize(x->f.size());
  std::vector<int64_t> idx(nd, 0);
  for (int64_t flat = 0; flat < x->numel(); ++flat) {
    int64_t rem = flat, src = 0;
    for (int k = 0; k < nd; ++k) {
      idx[k] = rem / ostr[k];
      rem %= ostr[k];
      src += idx[k] * xstr[perm[k]];
    }
    o->f[flat] = x->f[src];
  }
  return true;
});

static RegK r_mean("mean", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  double s = 0;
  for (float v : x->f) s += v;
  o->dims = {};
  o->f = {(float)(s / std::max<int64_t>(1, x->numel()))};
  return true;
});

static RegK r_argmax("arg_max", [](ExecCtx& c) {
  NTensor* x = c.In("X");
  NTensor* o = c.Out("Out");
  int64_t last = x->dims.back();
  int64_t rows = x->numel() / last;
  o->dims.assign(x->dims.begin(), x->dims.end() - 1);
  o->is_int = true;
  o->i.resize(rows);
  for (int64_t r = 0; r < rows; ++r) {
    const float* xr = &x->f[r * last];
    o->i[r] = (int64_t)(std::max_element(xr, xr + last) - xr);
  }
  return true;
});

// ---------------- predictor ----------------

class NativePredictor {
 public:
  std::string error;

  bool Load(const std::string& dir) {
    std::ifstream f(dir + "/__model__", std::ios::binary);
    if (!f) {
      error = "missing __model__ in " + dir;
      return false;
    }
    std::stringstream ss;
    ss << f.rdbuf();
    if (!model_.ParseFromString(ss.str())) {
      error = "bad __model__ proto";
      return false;
    }
    // params: PTC1 combined file
    std::string ppath = dir + "/__params__";
    CombineReader* r = CombineLoad(ppath.c_str());
    if (r) {
      if (!r->complete) {
        error = "truncated __params__";
        delete r;
        return false;
      }
      for (auto& [name, t] : r->entries) {
        NTensor nt;
        nt.dims = t.dims;
        const char* src = t.data.data();
        size_t nb = t.data.size();
        switch (t.dtype) {  // PTT1 codes → f32/i64 working storage
          case 1:  // float32
            nt.f.resize(nb / 4);
            memcpy(nt.f.data(), src, nb);
            break;
          case 2: {  // float64 → f32
            nt.f.resize(nb / 8);
            const double* d = (const double*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) nt.f[k] = (float)d[k];
            break;
          }
          case 3: {  // int32 → i64
            nt.is_int = true;
            nt.i.resize(nb / 4);
            const int32_t* d = (const int32_t*)src;
            for (size_t k = 0; k < nt.i.size(); ++k) nt.i[k] = d[k];
            break;
          }
          case 4:  // int64
            nt.is_int = true;
            nt.i.resize(nb / 8);
            memcpy(nt.i.data(), src, nb);
            break;
          case 5: case 8: {  // bool/uint8 → i64
            nt.is_int = true;
            nt.i.resize(nb);
            for (size_t k = 0; k < nb; ++k) nt.i[k] = (int64_t)(int8_t)src[k];
            break;
          }
          case 9: {  // int8: kept quantized for the quantized_* kernels
            nt.is_q = true;
            nt.q.resize(nb);
            memcpy(nt.q.data(), src, nb);
            break;
          }
          case 6: {  // uint16 carries bf16 bit patterns → f32
            nt.f.resize(nb / 2);
            const uint16_t* d = (const uint16_t*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) {
              uint32_t bits = ((uint32_t)d[k]) << 16;
              memcpy(&nt.f[k], &bits, 4);
            }
            break;
          }
          case 7: {  // float16 → f32
            nt.f.resize(nb / 2);
            const uint16_t* d = (const uint16_t*)src;
            for (size_t k = 0; k < nt.f.size(); ++k) {
              uint16_t h = d[k];
              uint32_t sign = (uint32_t)(h & 0x8000) << 16;
              uint32_t expo = (h >> 10) & 0x1f;
              uint32_t mant = h & 0x3ff;
              uint32_t bits;
              if (expo == 0) {
                if (mant == 0) {
                  bits = sign;
                } else {  // subnormal: normalize
                  int e = -1;
                  do { mant <<= 1; ++e; } while (!(mant & 0x400));
                  bits = sign | ((uint32_t)(127 - 15 - e) << 23)
                       | ((mant & 0x3ff) << 13);
                }
              } else if (expo == 31) {
                bits = sign | 0x7f800000u | (mant << 13);
              } else {
                bits = sign | ((expo - 15 + 127) << 23) | (mant << 13);
              }
              memcpy(&nt.f[k], &bits, 4);
            }
            break;
          }
          default:
            error = "unsupported param dtype code " +
                    std::to_string((int)t.dtype) + " for " + name;
            delete r;
            return false;
        }
        params_[name] = std::move(nt);
      }
      delete r;
    }
    return true;
  }

  void SetInput(const std::string& name, const int64_t* dims, int ndim,
                const float* data) {
    NTensor t;
    t.dims.assign(dims, dims + ndim);
    t.f.assign(data, data + t.numel());
    feeds_[name] = std::move(t);
  }

  void SetInputI64(const std::string& name, const int64_t* dims, int ndim,
                   const int64_t* data) {
    NTensor t;
    t.dims.assign(dims, dims + ndim);
    t.i.assign(data, data + t.numel());
    t.is_int = true;
    feeds_[name] = std::move(t);
  }

  // level-1 lod offsets for an already-set input (packed sequence rows)
  bool SetInputLod(const std::string& name, const int64_t* offsets, int n) {
    auto it = feeds_.find(name);
    if (it == feeds_.end()) return false;
    it->second.lod.assign(offsets, offsets + n);
    return true;
  }

  bool Run(const std::vector<std::string>& fetch_names) {
    for (const auto& n : model_.feed_names()) {
      if (!feeds_.count(n)) {
        error = "input not set: " + n;
        return false;
      }
    }
    ExecCtx ctx;
    ctx.params = &params_;
    ctx.mutable_params = &params_;  // sgd updates in pure-C++ training
    for (auto& [k, v] : feeds_) ctx.vars[k] = v;
    const auto& block = model_.program().blocks(0);
    ctx.block = &block;
    int op_idx = -1;
    for (const auto& op : block.ops()) {
      ++op_idx;
      if (op.type() == "feed" || op.type() == "fetch") continue;
      auto it = Registry().find(op.type());
      if (it == Registry().end()) {
        error = "no native kernel for op: " + op.type();
        return false;
      }
      // all declared inputs must exist before the kernel dereferences
      // them (grad vars appear once jax_autodiff has run)
      for (const auto& s : op.inputs())
        for (const auto& arg : s.args())
          if (!ctx.vars.count(arg) && !params_.count(arg)) {
            error = "op " + op.type() + ": input var not set: " + arg;
            return false;
          }
      ctx.op = &op;
      ctx.op_index = op_idx;
      if (!it->second(ctx)) {
        error = "op " + op.type() + " failed: " + ctx.error;
        return false;
      }
    }
    fetches_.clear();
    for (const auto& n : fetch_names) {
      auto it = ctx.vars.find(n);
      if (it != ctx.vars.end()) {
        fetches_.push_back({n, it->second});
        continue;
      }
      auto pit = params_.find(n);
      if (pit == params_.end()) {
        error = "fetch var missing: " + n;
        return false;
      }
      fetches_.push_back({n, pit->second});
    }
    return true;
  }

  const ptframework::InferenceModel& model() const { return model_; }
  std::vector<std::pair<std::string, NTensor>> fetches_;

 private:
  ptframework::InferenceModel model_;
  std::unordered_map<std::string, NTensor> params_;
  std::unordered_map<std::string, NTensor> feeds_;
};

}  // namespace ptcore

// ---------------- C API ----------------

using ptcore::NativePredictor;

extern "C" {

void* pt_pred_create(const char* model_dir) {
  auto* p = new NativePredictor;
  if (!p->Load(model_dir)) {
    // keep object alive so caller can read the error, flag via negative
    // handle convention is awkward in ctypes: expose error through object
  }
  return p;
}
const char* pt_pred_error(void* h) {
  return ((NativePredictor*)h)->error.c_str();
}
int pt_pred_feed_count(void* h) {
  return ((NativePredictor*)h)->model().feed_names_size();
}
const char* pt_pred_feed_name(void* h, int i) {
  return ((NativePredictor*)h)->model().feed_names(i).c_str();
}
int pt_pred_fetch_count(void* h) {
  return ((NativePredictor*)h)->model().fetch_names_size();
}
const char* pt_pred_fetch_name(void* h, int i) {
  return ((NativePredictor*)h)->model().fetch_names(i).c_str();
}
void pt_pred_set_input(void* h, const char* name, const int64_t* dims,
                       int ndim, const float* data) {
  ((NativePredictor*)h)->SetInput(name, dims, ndim, data);
}
void pt_pred_set_input_i64(void* h, const char* name, const int64_t* dims,
                           int ndim, const int64_t* data) {
  ((NativePredictor*)h)->SetInputI64(name, dims, ndim, data);
}
int pt_pred_set_input_lod(void* h, const char* name,
                          const int64_t* offsets, int n) {
  return ((NativePredictor*)h)->SetInputLod(name, offsets, n) ? 0 : -1;
}
int pt_pred_run(void* h) {
  auto* p = (NativePredictor*)h;
  std::vector<std::string> fetches;
  for (const auto& n : p->model().fetch_names()) fetches.push_back(n);
  return p->Run(fetches) ? 0 : -1;
}
int pt_pred_out_ndim(void* h, int i) {
  return (int)((NativePredictor*)h)->fetches_[i].second.dims.size();
}
void pt_pred_out_dims(void* h, int i, int64_t* out) {
  auto& d = ((NativePredictor*)h)->fetches_[i].second.dims;
  memcpy(out, d.data(), d.size() * 8);
}
int pt_pred_out_is_int(void* h, int i) {
  return ((NativePredictor*)h)->fetches_[i].second.is_int ? 1 : 0;
}
void pt_pred_out_copy(void* h, int i, void* out) {
  auto& t = ((NativePredictor*)h)->fetches_[i].second;
  if (t.is_int)
    memcpy(out, t.i.data(), t.i.size() * 8);
  else
    memcpy(out, t.f.data(), t.f.size() * 4);
}
void pt_pred_destroy(void* h) { delete (NativePredictor*)h; }

}  // extern "C"
